// Native libsvm/ffm batch parser — the TPU-framework equivalent of the
// reference's multi-threaded C++ `FmParser` TF op (SURVEY.md §2 #1).
//
// Exposed as a C ABI for ctypes (no pybind11 in this environment).  The
// Python oracle is fast_tffm_tpu/data/libsvm.py; tests enforce bit-exact
// agreement (same MurmurHash64A, same label/field/id/val semantics).
//
// Threading model: the caller hands one contiguous text buffer plus line
// offsets; lines are split evenly across worker threads, each writing its
// own disjoint rows of the output arrays — no locks in the hot path.
//
// Build: g++ -O3 -std=c++17 -shared -fPIC -pthread fm_parser.cc -o libfm_parser.so
// (plain -O3, no -march=native: measured faster here, and the cached .so
// stays portable across CPUs — see data/native.py)

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <thread>
#include <vector>
#include <atomic>

namespace {

constexpr uint64_t kMurmurM = 0xc6a4a7935bd1e995ULL;
constexpr int kMurmurR = 47;

// MurmurHash64A, seed 0 — must match libsvm.murmur64 bit-for-bit.
uint64_t Murmur64(const char* data, size_t len) {
  uint64_t h = 0 ^ (static_cast<uint64_t>(len) * kMurmurM);
  const size_t n_blocks = len / 8;
  for (size_t i = 0; i < n_blocks; ++i) {
    uint64_t k;
    std::memcpy(&k, data + i * 8, 8);  // little-endian hosts only (x86/ARM)
    k *= kMurmurM;
    k ^= k >> kMurmurR;
    k *= kMurmurM;
    h ^= k;
    h *= kMurmurM;
  }
  const size_t tail_len = len & 7;
  if (tail_len) {
    uint64_t t = 0;
    std::memcpy(&t, data + n_blocks * 8, tail_len);
    h ^= t;
    h *= kMurmurM;
  }
  h ^= h >> kMurmurR;
  h *= kMurmurM;
  h ^= h >> kMurmurR;
  return h;
}

inline bool IsSpace(char c) {
  return c == ' ' || c == '\t' || c == '\r' || c == '\n' || c == '\v' ||
         c == '\f';
}

// Blank/comment test shared by both batch entry points (their rows get
// weight 0; ParseLine keeps its own early-return as a safety net for
// direct calls, where such a row merely stays zeroed).
inline bool BlankOrComment(const char* s, const char* e) {
  while (s < e && IsSpace(*s)) ++s;
  return s >= e || *s == '#';
}

struct Parser {
  uint64_t vocabulary_size;
  int max_features;
  bool hash_feature_id;
  int field_num;
  int num_threads;
};

// Python-compatible modulo (result always in [0, m)).
inline int64_t PyMod(int64_t x, int64_t m) {
  int64_t r = x % m;
  return r < 0 ? r + m : r;
}

// Fast integer parse of [s, e): full-token decimal with optional sign.
// (strtoll is several times slower due to locale/errno handling.)
inline bool ParseInt(const char* s, const char* e, int64_t* out) {
  if (s >= e) return false;
  bool neg = false;
  if (*s == '+' || *s == '-') {
    neg = (*s == '-');
    ++s;
  }
  if (s >= e) return false;
  // Skip leading zeros so only SIGNIFICANT digits count toward the cap —
  // Python's int() accepts "000...0123" and so must we (bit-exactness with
  // the oracle).  At least one digit remains semantically: all-zero input
  // falls through with v == 0, digits == 0.
  while (s < e && *s == '0') ++s;
  uint64_t v = 0;
  int digits = 0;
  for (; s < e; ++s) {
    char c = *s;
    if (c < '0' || c > '9') return false;
    // 19 significant digits max 9999999999999999999 < 2^64, so v never
    // wraps; the int64 limit check below is the real range guard.
    if (++digits > 19) return false;
    v = v * 10 + (c - '0');
  }
  uint64_t limit = neg ? (1ull << 63) : (1ull << 63) - 1;
  if (v > limit) return false;
  // Negate in unsigned space: -INT64_MIN via signed unary minus is UB.
  *out = neg ? static_cast<int64_t>(0ull - v) : static_cast<int64_t>(v);
  return true;
}

// Parses a decimal feature id of ANY length and reduces it mod m,
// matching Python's arbitrary-precision int(token) % m exactly
// (including the non-negative result for negative ids). Requires
// m < 2^59 so r*10 + digit cannot overflow uint64.
//
// Fast path: ids with <= 19 significant digits (everything real data
// contains) accumulate without reduction and take ONE final mod —
// per-digit "% m" costs a 20-40 cycle divide per digit and dominated the
// whole parse at ~7-digit Criteo ids.  Longer ids reduce per digit.
inline bool ParseIdMod(const char* s, const char* e, uint64_t m,
                       int64_t* out) {
  if (s >= e) return false;
  bool neg = false;
  if (*s == '+' || *s == '-') {
    neg = (*s == '-');
    ++s;
  }
  if (s >= e) return false;
  // Skip leading zeros so only significant digits count toward the 19.
  while (s < e && *s == '0') ++s;
  uint64_t r = 0;
  if (e - s <= 19) {
    for (; s < e; ++s) {
      char c = *s;
      if (c < '0' || c > '9') return false;
      r = r * 10 + static_cast<uint64_t>(c - '0');
    }
    r %= m;  // 19 digits < 2^64: no overflow before the single mod
  } else {
    for (; s < e; ++s) {
      char c = *s;
      if (c < '0' || c > '9') return false;
      r = (r * 10 + static_cast<uint64_t>(c - '0')) % m;
    }
  }
  if (neg && r) r = m - r;
  *out = static_cast<int64_t>(r);
  return true;
}

const double kPow10[] = {1e0,  1e1,  1e2,  1e3,  1e4,  1e5,  1e6,  1e7,
                         1e8,  1e9,  1e10, 1e11, 1e12, 1e13, 1e14, 1e15,
                         1e16, 1e17, 1e18, 1e19, 1e20, 1e21, 1e22};

// Fast float parse of the full token [s, e). The fast path covers
// [+-]digits[.digits] with <=15 significant digits — mantissa and power of
// ten are then both exact doubles, so the single division is correctly
// rounded and matches strtod (and Python's float()) bit-for-bit. Anything
// else (exponents, inf/nan, long mantissas) falls back to strtof.
inline bool ParseFloat(const char* s, const char* e, float* out) {
  const char* p = s;
  bool neg = false;
  if (p < e && (*p == '+' || *p == '-')) {
    neg = (*p == '-');
    ++p;
  }
  uint64_t mant = 0;
  int digits = 0, frac = 0;
  bool any = false, dot = false, fast = true;
  for (; p < e; ++p) {
    char c = *p;
    if (c >= '0' && c <= '9') {
      if (digits < 15) {
        mant = mant * 10 + (c - '0');
        ++digits;
        if (dot) ++frac;
        any = true;
      } else {
        fast = false;
        break;
      }
    } else if (c == '.' && !dot) {
      dot = true;
    } else {
      fast = false;
      break;
    }
  }
  if (fast && any) {
    double v = static_cast<double>(mant) / kPow10[frac];
    *out = static_cast<float>(neg ? -v : v);
    return true;
  }
  // strtod accepts forms Python's float() rejects: hex floats ("0x10",
  // via 'x') and nan payloads ("nan(chars)", via '(').  The Python
  // oracle symmetrically rejects forms strtod can't parse (underscore
  // literals, Unicode digits); both sides pin to the ASCII intersection.
  for (const char* q = s; q < e; ++q) {
    if (*q == 'x' || *q == 'X' || *q == '(') return false;
  }
  char* endp = nullptr;
  // strtod then cast, NOT strtof: Python parses to float64 and numpy
  // rounds that to float32 (double rounding).  strtof's single rounding
  // differs by an ULP on >15-significant-digit tokens near f32 tie
  // midpoints — the oracle's two-step path is the contract.
  double v = std::strtod(s, &endp);
  if (endp != e || s == e) return false;
  *out = static_cast<float>(v);
  return true;
}

// Parses one line into row `row` of the outputs. Returns the number of
// feature tokens dropped by max_features truncation; -1 on malformed input.
int ParseLine(const Parser& p, const char* s, const char* end, int64_t row,
              float* labels, int32_t* ids, float* vals, int32_t* fields) {
  // Trim.
  while (s < end && IsSpace(*s)) ++s;
  while (end > s && IsSpace(end[-1])) --end;
  if (s >= end || *s == '#') return 0;  // blank/comment: row stays zeroed

  const char* label_end = s;
  while (label_end < end && !IsSpace(*label_end)) ++label_end;
  float label;
  // The label token must be fully consumed ("1x" is malformed, like
  // Python float("1x")).
  if (!ParseFloat(s, label_end, &label)) return -1;
  if (label == -1.0f) label = 0.0f;  // accept {-1,1} label convention
  labels[row] = label;

  const char* cur = label_end;
  int count = 0;
  int dropped = 0;
  int32_t* row_ids = ids + row * p.max_features;
  float* row_vals = vals + row * p.max_features;
  int32_t* row_fields = fields + row * p.max_features;

  while (cur < end) {
    while (cur < end && IsSpace(*cur)) ++cur;
    if (cur >= end) break;
    // One pass: find the token end and split on ':' as we go — up to 3
    // pieces: [field:]id[:val].
    const char* tok = cur;
    const char* c1 = nullptr;
    const char* c2 = nullptr;
    for (; cur < end && !IsSpace(*cur); ++cur) {
      if (*cur == ':') {
        if (!c1) {
          c1 = cur;
        } else if (!c2) {
          c2 = cur;
        } else {
          return -1;  // too many colons
        }
      }
    }
    const char* tok_end = cur;
    const char *id_s, *id_e;
    const char *val_s = nullptr, *val_e = nullptr;
    int64_t field = 0;
    if (c2) {  // field:id:val
      if (!ParseInt(tok, c1, &field)) return -1;  // empty/partial field
      id_s = c1 + 1;
      id_e = c2;
      val_s = c2 + 1;
      val_e = tok_end;
    } else if (c1) {  // id:val
      id_s = tok;
      id_e = c1;
      val_s = c1 + 1;
      val_e = tok_end;
    } else {  // bare id => val 1.0
      id_s = tok;
      id_e = tok_end;
    }

    // Validate BEFORE the truncation check so a malformed over-limit token
    // errors exactly like the Python oracle (which parses, then truncates).
    int64_t fid;
    if (p.hash_feature_id) {
      fid = static_cast<int64_t>(Murmur64(id_s, id_e - id_s) %
                                 p.vocabulary_size);
    } else {
      // int("") raises in Python: ParseIdMod rejects empty/partial ids,
      // and handles ids of any digit length (Python-int parity).
      if (!ParseIdMod(id_s, id_e, p.vocabulary_size, &fid)) return -1;
    }
    float v = 1.0f;
    if (val_s) {
      if (!ParseFloat(val_s, val_e, &v)) return -1;  // float("") raises
    }
    if (p.field_num > 0) field = PyMod(field, p.field_num);

    if (count >= p.max_features) {
      ++dropped;
      continue;
    }
    row_ids[count] = static_cast<int32_t>(fid);
    row_vals[count] = v;
    row_fields[count] = static_cast<int32_t>(field);
    ++count;
  }
  return dropped;
}

}  // namespace

// Shared parallel harness for the batch entry points: splits [0, n_lines)
// across the parser's threads, aggregates truncation counts, and tracks
// the first malformed line. per_line(i, local_dropped) returns false on
// malformed input. Returns total dropped, or -(first_bad_index + 1).
template <typename F>
int64_t RunLines(const Parser& p, int64_t n_lines, F&& per_line) {
  std::atomic<int64_t> dropped{0};
  std::atomic<int64_t> first_bad{INT64_MAX};

  auto work = [&](int64_t begin, int64_t stop) {
    int64_t local_dropped = 0;
    for (int64_t i = begin; i < stop; ++i) {
      if (!per_line(i, &local_dropped)) {
        int64_t cur = first_bad.load(std::memory_order_relaxed);
        while (i < cur &&
               !first_bad.compare_exchange_weak(cur, i,
                                                std::memory_order_relaxed)) {
        }
        break;
      }
    }
    dropped.fetch_add(local_dropped, std::memory_order_relaxed);
  };

  int nt = p.num_threads;
  if (nt <= 1 || n_lines < 2 * nt) {
    work(0, n_lines);
  } else {
    std::vector<std::thread> threads;
    int64_t chunk = (n_lines + nt - 1) / nt;
    for (int t = 0; t < nt; ++t) {
      int64_t b = t * chunk;
      int64_t e = b + chunk < n_lines ? b + chunk : n_lines;
      if (b >= e) break;
      threads.emplace_back(work, b, e);
    }
    for (auto& th : threads) th.join();
  }
  int64_t bad = first_bad.load();
  if (bad != INT64_MAX) return -(bad + 1);
  return dropped.load();
}

extern "C" {

void* fm_parser_create(uint64_t vocabulary_size, int max_features,
                       int hash_feature_id, int field_num, int num_threads) {
  if (vocabulary_size == 0 || vocabulary_size >= (1ULL << 59)) {
    return nullptr;  // ParseIdMod requires m < 2^59 (r*10+9 in uint64)
  }
  Parser* p = new Parser();
  p->vocabulary_size = vocabulary_size;
  p->max_features = max_features;
  p->hash_feature_id = hash_feature_id != 0;
  p->field_num = field_num;
  p->num_threads = num_threads < 1 ? 1 : num_threads;
  return p;
}

void fm_parser_destroy(void* handle) { delete static_cast<Parser*>(handle); }

// Parse n_lines lines (buf + offsets, offsets has n_lines+1 entries) into
// the first n_lines rows of the [batch_size, max_features] outputs.  All
// output arrays must be pre-zeroed by the caller (padding convention).
// weights_in may be null (-> 1.0 for parsed rows).  Blank/comment lines
// become weight-0 rows (same convention as parse_raw — a weight-1 empty
// row would train w0 on a phantom label-0 example).  Returns total
// dropped (truncated) feature count, or -(first_bad_index + 1) if a
// line was malformed (callers decode the line number from it).
int64_t fm_parser_parse(void* handle, const char* buf,
                        const int64_t* offsets, int64_t n_lines,
                        float* labels, int32_t* ids, float* vals,
                        int32_t* fields, float* weights,
                        const float* weights_in) {
  const Parser& p = *static_cast<Parser*>(handle);
  return RunLines(p, n_lines, [&](int64_t i, int64_t* local_dropped) {
    const char* s = buf + offsets[i];
    const char* e = buf + offsets[i + 1];
    if (BlankOrComment(s, e)) {
      weights[i] = 0.0f;
      return true;
    }
    int d = ParseLine(p, s, e, i, labels, ids, vals, fields);
    if (d < 0) return false;
    *local_dropped += d;
    weights[i] = weights_in ? weights_in[i] : 1.0f;
    return true;
  });
}

uint64_t fm_parser_murmur64(const char* data, int64_t len) {
  return Murmur64(data, len);
}

// Scans buf for line-start offsets (byte after each '\n', plus offset 0).
// Writes up to max_out offsets; returns the number found (may exceed
// max_out to signal the caller to grow its buffer). The caller derives
// line ends from the next start (ParseLine trims the trailing newline).
int64_t fm_parser_find_lines(const char* buf, int64_t len, int64_t* out,
                             int64_t max_out) {
  int64_t count = 0;
  if (len <= 0) return 0;
  if (count < max_out) out[count] = 0;
  ++count;
  const char* p = buf;
  const char* end = buf + len;
  while ((p = static_cast<const char*>(memchr(p, '\n', end - p)))) {
    ++p;
    if (p >= end) break;  // trailing newline: no new line starts after it
    if (count < max_out) out[count] = p - buf;
    ++count;
  }
  return count;
}

// Like fm_parser_parse but takes per-line [start, end) extents — lines
// need not be contiguous or ordered in buf (the pipeline's line-level
// shuffle hands a permuted view of a window) — and marks blank/comment
// lines with weight 0 (the raw-chunk path has no Python-side blank
// filtering). Lines that parse get weight weights_in[i] (or 1.0). Same
// return convention.
int64_t fm_parser_parse_raw(void* handle, const char* buf,
                            const int64_t* starts, const int64_t* ends,
                            int64_t n_lines, float* labels, int32_t* ids,
                            float* vals, int32_t* fields, float* weights,
                            const float* weights_in) {
  const Parser& p = *static_cast<Parser*>(handle);
  return RunLines(p, n_lines, [&](int64_t i, int64_t* local_dropped) {
    const char* s = buf + starts[i];
    const char* e = buf + ends[i];
    if (BlankOrComment(s, e)) {
      weights[i] = 0.0f;
      return true;
    }
    int d = ParseLine(p, s, e, i, labels, ids, vals, fields);
    if (d < 0) return false;
    *local_dropped += d;
    weights[i] = weights_in ? weights_in[i] : 1.0f;
    return true;
  });
}

// Host-side sparse-apply prep: stable-sort the batch's flat ids and
// derive, in one linear scan, every id-only quantity the tile apply
// kernels need (rebuilding what ops/sparse_apply._prep computes on
// device).  On v5e the device-side XLA sort alone costs ~10.8 ms/step
// at Criteo shapes; here it rides the parser's pipeline threads,
// overlapped with device compute.  Must match the device path exactly:
// the sort is STABLE (duplicate ids keep occurrence order, like
// jax.lax.sort_key_val with an iota payload), padded tail slots get
// sentinel id == vocab (sorted last, landing in no tile since
// boundaries stop at vocab).
//
// In:  ids [n] int32 in [0, vocab); n <= n_pad; n_pad a chunk multiple.
// Out (caller-allocated):
//   perm       [n_pad] i32  occurrence index per sorted position
//   upos       [n_pad] i32  unique-segment index per sorted position
//   lrow_last  [n_pad] f32  (sidx % tile) if segment end else 0
//   starts     [n_pad/chunk]     i32  upos at each chunk start
//   firsts     [n_pad/chunk + 1] i32  segment-start flag at chunk starts
//                                     (+1 trailing sentinel, always 1)
//   ends       [n_pad/chunk]     i32  upos at each chunk end
//   tile_start [vocab/tile + 1]  i32  unique index of first id >= t*tile
// Returns the number of unique real ids (excluding sentinels), or -1 on
// bad arguments.
int64_t fm_sort_meta(const int32_t* ids, int64_t n, int64_t n_pad,
                     int64_t vocab, int64_t chunk, int64_t tile,
                     int32_t* perm, int32_t* upos, float* lrow_last,
                     int32_t* starts, int32_t* firsts, int32_t* ends,
                     int32_t* tile_start) {
  if (n < 0 || n > n_pad || n_pad <= 0 || n_pad % chunk || chunk <= 0 ||
      tile <= 0 || vocab <= 0 || vocab % tile || vocab > INT32_MAX) {
    return -1;
  }
  const int64_t n_chunks = n_pad / chunk;
  const int64_t n_tiles = vocab / tile;
  if (n_pad > (1LL << 31)) return -1;  // index must fit the low 31 bits
  // Stable sort of packed (id << 31 | index) uint64 keys, MSB-bucket
  // first: one scattered pass distributes keys into ~2048 top-bit
  // buckets (the only cache-hostile pass — LSD radix paid this miss
  // cost on EVERY pass), then each small bucket is finished with
  // cache-resident 11-bit counting passes over the remaining low id
  // bits.  The occurrence index lives in the low 31 key bits and is
  // never sorted on, so equal ids keep occurrence order — matching
  // jax.lax.sort_key_val with an iota payload.  Sentinel-padded tail:
  // id == vocab sorts after every real id (own top-bit bucket or own
  // low-bit value).
  constexpr int kIdxBits = 31;
  constexpr int kRadixBits = 11;
  constexpr int64_t kRadix = 1 << kRadixBits;
  int id_bits = 0;
  while ((static_cast<uint64_t>(vocab) >> id_bits) != 0) ++id_bits;
  // Up to 12 top bits (<= 4097 buckets, ~32KB of bucket offsets): the
  // common vocab = 2^22 (23 id bits incl. the sentinel) then leaves 11
  // low bits — exactly one cache-hot pass per bucket.
  const int top_bits = id_bits < 12 ? id_bits : 12;
  const int lo_bits = id_bits - top_bits;
  const int64_t n_buckets =
      (static_cast<int64_t>(vocab) >> lo_bits) + 1;  // top-bits range
  std::vector<uint64_t> key(n_pad), key2(n_pad);
  for (int64_t i = 0; i < n_pad; ++i) {
    uint64_t id = static_cast<uint64_t>(vocab);  // sentinel for the pad tail
    if (i < n) {
      // Fail loud on out-of-range ids (matching the argument checks
      // above): a negative id cast to unsigned, or id >= vocab, would
      // index the bucket histogram/scatter out of bounds — heap
      // corruption, not just a wrong answer.  Callers fall back to the
      // always-correct device sort on -1.
      const int32_t v = ids[i];
      if (v < 0 || v >= vocab) return -1;
      id = static_cast<uint32_t>(v);
    }
    key[i] = (id << kIdxBits) | static_cast<uint64_t>(i);
  }
  // Pass A+B: bucket histogram over the top id bits, then scatter.
  std::vector<int64_t> bstart(n_buckets + 1, 0);
  const int top_shift = kIdxBits + lo_bits;
  for (int64_t i = 0; i < n_pad; ++i) {
    ++bstart[(key[i] >> top_shift) + 1];
  }
  for (int64_t b = 0; b < n_buckets; ++b) bstart[b + 1] += bstart[b];
  {
    std::vector<int64_t> pos(bstart.begin(), bstart.end() - 1);
    for (int64_t i = 0; i < n_pad; ++i) {
      key2[pos[key[i] >> top_shift]++] = key[i];
    }
  }
  // Per bucket: LSD counting passes over the low id bits (cache-hot:
  // buckets average n/2048 keys).  lo_bits == 0 means a bucket holds
  // one id value only — already sorted (scatter preserved order).
  uint64_t* k_src = key2.data();  // scan reads from k_src when done
  uint64_t* k_dst = key.data();
  if (lo_bits > 0) {
    int64_t count[kRadix + 1];
    for (int64_t b = 0; b < n_buckets; ++b) {
      uint64_t* src = k_src + bstart[b];
      uint64_t* dst = k_dst + bstart[b];
      const int64_t m = bstart[b + 1] - bstart[b];
      if (m <= 1) {
        if (m == 1) dst[0] = src[0];
        continue;
      }
      for (int shift = 0; shift < lo_bits; shift += kRadixBits) {
        const int bits = std::min(kRadixBits, lo_bits - shift);
        const uint64_t mask = (1u << bits) - 1;
        std::fill(count, count + (1 << bits) + 1, 0);
        for (int64_t i = 0; i < m; ++i) {
          ++count[((src[i] >> (kIdxBits + shift)) & mask) + 1];
        }
        for (int64_t v = 0; v < (1 << bits); ++v) count[v + 1] += count[v];
        for (int64_t i = 0; i < m; ++i) {
          dst[count[(src[i] >> (kIdxBits + shift)) & mask]++] = src[i];
        }
        std::swap(src, dst);
      }
      // After the pass loop `src` points at the buffer holding the
      // sorted run (the swaps alternate); normalize every bucket into
      // k_dst's region so one buffer holds the full sorted sequence.
      if (src != k_dst + bstart[b]) {
        std::memcpy(k_dst + bstart[b], src, m * sizeof(uint64_t));
      }
    }
    k_src = k_dst;  // scan reads the normalized buffer
  }
  // One scan: uniques, chunk metadata, tile boundaries.
  int64_t nu = 0;        // uniques so far (including sentinels at tail)
  int64_t nu_real = 0;   // uniques among real ids
  int64_t t = 0;         // next tile boundary to place (value t * tile)
  for (int64_t p = 0; p < n_pad; ++p) {
    const int64_t id = static_cast<int64_t>(k_src[p] >> kIdxBits);
    const bool first = (p == 0) || (id != static_cast<int64_t>(
                                        k_src[p - 1] >> kIdxBits));
    if (first) {
      while (t <= n_tiles && t * tile <= id) {
        tile_start[t++] = static_cast<int32_t>(nu);
      }
      ++nu;
      if (id < vocab) ++nu_real;
    }
    perm[p] = static_cast<int32_t>(k_src[p] & ((1u << kIdxBits) - 1));
    upos[p] = static_cast<int32_t>(nu - 1);
    const bool last = (p + 1 == n_pad) || (id != static_cast<int64_t>(
                                               k_src[p + 1] >> kIdxBits));
    lrow_last[p] = last ? static_cast<float>(id % tile) : 0.0f;
    if (p % chunk == 0) {
      starts[p / chunk] = static_cast<int32_t>(nu - 1);
      firsts[p / chunk] = first ? 1 : 0;
    }
    if ((p + 1) % chunk == 0) {
      ends[p / chunk] = static_cast<int32_t>(nu - 1);
    }
  }
  while (t <= n_tiles) tile_start[t++] = static_cast<int32_t>(nu);
  firsts[n_chunks] = 1;
  return nu_real;
}

}  // extern "C"
