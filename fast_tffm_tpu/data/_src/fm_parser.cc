// Native libsvm/ffm batch parser — the TPU-framework equivalent of the
// reference's multi-threaded C++ `FmParser` TF op (SURVEY.md §2 #1).
//
// Exposed as a C ABI for ctypes (no pybind11 in this environment).  The
// Python oracle is fast_tffm_tpu/data/libsvm.py; tests enforce bit-exact
// agreement (same MurmurHash64A, same label/field/id/val semantics).
//
// Threading model: the caller hands one contiguous text buffer plus line
// offsets; lines are split evenly across worker threads, each writing its
// own disjoint rows of the output arrays — no locks in the hot path.
//
// Build: g++ -O3 -march=native -shared -fPIC -pthread fm_parser.cc -o libfm_parser.so

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <thread>
#include <vector>
#include <atomic>

namespace {

constexpr uint64_t kMurmurM = 0xc6a4a7935bd1e995ULL;
constexpr int kMurmurR = 47;

// MurmurHash64A, seed 0 — must match libsvm.murmur64 bit-for-bit.
uint64_t Murmur64(const char* data, size_t len) {
  uint64_t h = 0 ^ (static_cast<uint64_t>(len) * kMurmurM);
  const size_t n_blocks = len / 8;
  for (size_t i = 0; i < n_blocks; ++i) {
    uint64_t k;
    std::memcpy(&k, data + i * 8, 8);  // little-endian hosts only (x86/ARM)
    k *= kMurmurM;
    k ^= k >> kMurmurR;
    k *= kMurmurM;
    h ^= k;
    h *= kMurmurM;
  }
  const size_t tail_len = len & 7;
  if (tail_len) {
    uint64_t t = 0;
    std::memcpy(&t, data + n_blocks * 8, tail_len);
    h ^= t;
    h *= kMurmurM;
  }
  h ^= h >> kMurmurR;
  h *= kMurmurM;
  h ^= h >> kMurmurR;
  return h;
}

inline bool IsSpace(char c) {
  return c == ' ' || c == '\t' || c == '\r' || c == '\n' || c == '\v' ||
         c == '\f';
}

struct Parser {
  uint64_t vocabulary_size;
  int max_features;
  bool hash_feature_id;
  int field_num;
  int num_threads;
};

// Python-compatible modulo (result always in [0, m)).
inline int64_t PyMod(int64_t x, int64_t m) {
  int64_t r = x % m;
  return r < 0 ? r + m : r;
}

// Parses one line into row `row` of the outputs. Returns the number of
// feature tokens dropped by max_features truncation; -1 on malformed input.
int ParseLine(const Parser& p, const char* s, const char* end, int64_t row,
              float* labels, int32_t* ids, float* vals, int32_t* fields) {
  // Trim.
  while (s < end && IsSpace(*s)) ++s;
  while (end > s && IsSpace(end[-1])) --end;
  if (s >= end || *s == '#') return 0;  // blank/comment: row stays zeroed

  char* next = nullptr;
  float label = std::strtof(s, &next);
  // The label token must be fully consumed ("1x" is malformed, like
  // Python float("1x")).
  if (next == s || (next != end && !IsSpace(*next))) return -1;
  if (label == -1.0f) label = 0.0f;  // accept {-1,1} label convention
  labels[row] = label;

  const char* cur = next;
  int count = 0;
  int dropped = 0;
  int32_t* row_ids = ids + row * p.max_features;
  float* row_vals = vals + row * p.max_features;
  int32_t* row_fields = fields + row * p.max_features;

  while (cur < end) {
    while (cur < end && IsSpace(*cur)) ++cur;
    if (cur >= end) break;
    const char* tok = cur;
    while (cur < end && !IsSpace(*cur)) ++cur;
    const char* tok_end = cur;

    // Split token on ':' — up to 3 pieces: [field:]id[:val]
    const char* c1 = nullptr;
    const char* c2 = nullptr;
    for (const char* q = tok; q < tok_end; ++q) {
      if (*q == ':') {
        if (!c1) {
          c1 = q;
        } else if (!c2) {
          c2 = q;
        } else {
          return -1;  // too many colons
        }
      }
    }
    const char *id_s, *id_e;
    const char *val_s = nullptr, *val_e = nullptr;
    int64_t field = 0;
    if (c2) {  // field:id:val
      char* fend = nullptr;
      field = std::strtoll(tok, &fend, 10);
      if (tok == c1 || fend != c1) return -1;  // empty/partial field
      id_s = c1 + 1;
      id_e = c2;
      val_s = c2 + 1;
      val_e = tok_end;
    } else if (c1) {  // id:val
      id_s = tok;
      id_e = c1;
      val_s = c1 + 1;
      val_e = tok_end;
    } else {  // bare id => val 1.0
      id_s = tok;
      id_e = tok_end;
    }

    // Validate BEFORE the truncation check so a malformed over-limit token
    // errors exactly like the Python oracle (which parses, then truncates).
    int64_t fid;
    if (p.hash_feature_id) {
      fid = static_cast<int64_t>(Murmur64(id_s, id_e - id_s) %
                                 p.vocabulary_size);
    } else {
      char* iend = nullptr;
      int64_t raw = std::strtoll(id_s, &iend, 10);
      // int("") raises in Python: require a nonempty, fully-consumed id.
      if (id_s == id_e || iend != id_e) return -1;
      fid = PyMod(raw, static_cast<int64_t>(p.vocabulary_size));
    }
    float v = 1.0f;
    if (val_s) {
      char* vend = nullptr;
      v = std::strtof(val_s, &vend);
      if (val_s == val_e || vend != val_e) return -1;  // float("") raises
    }
    if (p.field_num > 0) field = PyMod(field, p.field_num);

    if (count >= p.max_features) {
      ++dropped;
      continue;
    }
    row_ids[count] = static_cast<int32_t>(fid);
    row_vals[count] = v;
    row_fields[count] = static_cast<int32_t>(field);
    ++count;
  }
  return dropped;
}

}  // namespace

extern "C" {

void* fm_parser_create(uint64_t vocabulary_size, int max_features,
                       int hash_feature_id, int field_num, int num_threads) {
  Parser* p = new Parser();
  p->vocabulary_size = vocabulary_size;
  p->max_features = max_features;
  p->hash_feature_id = hash_feature_id != 0;
  p->field_num = field_num;
  p->num_threads = num_threads < 1 ? 1 : num_threads;
  return p;
}

void fm_parser_destroy(void* handle) { delete static_cast<Parser*>(handle); }

// Parse n_lines lines (buf + offsets, offsets has n_lines+1 entries) into
// the first n_lines rows of the [batch_size, max_features] outputs.  All
// output arrays must be pre-zeroed by the caller (padding convention).
// weights_in may be null (-> 1.0 for parsed rows).  Returns total dropped
// (truncated) feature count, or -1 if any line was malformed.
int64_t fm_parser_parse(void* handle, const char* buf,
                        const int64_t* offsets, int64_t n_lines,
                        float* labels, int32_t* ids, float* vals,
                        int32_t* fields, float* weights,
                        const float* weights_in) {
  const Parser& p = *static_cast<Parser*>(handle);
  std::atomic<int64_t> dropped{0};
  // First malformed line index, or INT64_MAX if none (min across threads).
  std::atomic<int64_t> first_bad{INT64_MAX};

  auto work = [&](int64_t begin, int64_t stop) {
    int64_t local_dropped = 0;
    for (int64_t i = begin; i < stop; ++i) {
      int d = ParseLine(p, buf + offsets[i], buf + offsets[i + 1], i, labels,
                        ids, vals, fields);
      if (d < 0) {
        int64_t cur = first_bad.load(std::memory_order_relaxed);
        while (i < cur &&
               !first_bad.compare_exchange_weak(cur, i,
                                                std::memory_order_relaxed)) {
        }
        return;
      }
      local_dropped += d;
      weights[i] = weights_in ? weights_in[i] : 1.0f;
    }
    dropped.fetch_add(local_dropped, std::memory_order_relaxed);
  };

  int nt = p.num_threads;
  if (nt <= 1 || n_lines < 2 * nt) {
    work(0, n_lines);
  } else {
    std::vector<std::thread> threads;
    int64_t chunk = (n_lines + nt - 1) / nt;
    for (int t = 0; t < nt; ++t) {
      int64_t b = t * chunk;
      int64_t e = b + chunk < n_lines ? b + chunk : n_lines;
      if (b >= e) break;
      threads.emplace_back(work, b, e);
    }
    for (auto& th : threads) th.join();
  }
  int64_t bad = first_bad.load();
  if (bad != INT64_MAX) return -(bad + 1);  // -(line_index + 1)
  return dropped.load();
}

uint64_t fm_parser_murmur64(const char* data, int64_t len) {
  return Murmur64(data, len);
}

}  // extern "C"
