"""Multiprocess parse workers: GIL-free ingest parsing.

``parse_processes > 0`` moves batch parsing out of the trainer process
into a pool of SPAWNED workers (never forked: a fork would inherit JAX's
runtime threads and held locks; a spawned child imports only numpy + the
data layer).  This is the rebuild's answer to the reference's free-running
C++ ``FmParser`` threads: the pure-Python parse fallback is GIL-bound no
matter what ``thread_num`` says, and even the ctypes path serializes its
Python-side batch assembly — worker processes sidestep both.

Parsed batches travel back over POSIX shared memory
(``multiprocessing.shared_memory``): the worker lays the batch's
contiguous numpy arrays (and, when host sort prep is on, the sort_meta
arrays — all shapes are static given the config) into ONE segment and
ships just the segment name over the result queue.  The parent maps the
segment and wraps zero-copy views, so the only post-parse copy is
``np.stack`` gathering the super-batch in ``stack_batches``.

Segment lifecycle (Python 3.10: no ``track=False``):

- the worker creates the segment, UNREGISTERS it from its resource
  tracker (the segment must outlive the worker's queue turnover), writes,
  and closes its own mapping;
- the parent attaches, immediately ``unlink()``\\ s (the name disappears;
  pages persist while mapped) and adopts the raw mmap out of the wrapper
  (``_adopt_mapping``) — the views' .base chain then owns the mapping,
  so the kernel reclaims the pages when the last view dies.
"""

from __future__ import annotations

import dataclasses
import os
import pickle
import queue as _queue
import time
from multiprocessing import resource_tracker, shared_memory
from typing import Optional

import numpy as np

from fast_tffm_tpu.data.libsvm import Batch, SortMeta


@dataclasses.dataclass(frozen=True)
class WorkerSpec:
    """Everything a spawned worker needs to parse (picklable; no FmConfig
    so children never import jax-adjacent modules)."""

    vocabulary_size: int
    max_features: int
    hash_feature_id: bool
    field_num: int
    batch_size: int
    use_native: bool  # parent's parser choice; children must match it
    sort_meta_spec: Optional[tuple]  # (vocab, chunk, tile) or None


_CORE = ("labels", "ids", "vals", "fields", "weights")
_META = ("perm", "upos", "lrow_last", "starts", "firsts", "ends",
         "tile_start")


def _layout(spec: WorkerSpec):
    """[(name, shape, dtype)] for the core batch and the sort_meta tail.

    Every shape is static given the spec — n_pad/n_chunks/n_tiles mirror
    native.sort_meta's padding math — so writer and reader agree on the
    segment layout without shipping shapes per batch.
    """
    b, f = spec.batch_size, spec.max_features
    core = [
        ("labels", (b,), np.float32),
        ("ids", (b, f), np.int32),
        ("vals", (b, f), np.float32),
        ("fields", (b, f), np.int32),
        ("weights", (b,), np.float32),
    ]
    meta: list = []
    if spec.sort_meta_spec is not None:
        vocab, chunk, tile = spec.sort_meta_spec
        n = b * f
        n_pad = -(-n // chunk) * chunk
        n_chunks = n_pad // chunk
        n_tiles = vocab // tile
        meta = [
            ("perm", (n_pad,), np.int32),
            ("upos", (n_pad,), np.int32),
            ("lrow_last", (n_pad,), np.float32),
            ("starts", (n_chunks,), np.int32),
            ("firsts", (n_chunks + 1,), np.int32),
            ("ends", (n_chunks,), np.int32),
            ("tile_start", (n_tiles + 1,), np.int32),
        ]
    return core, meta


def _nbytes(fields) -> int:
    return sum(
        int(np.prod(shape)) * np.dtype(dt).itemsize for _, shape, dt in fields
    )


def ship_batch(spec: WorkerSpec, batch: Batch, has_meta: bool) -> str:
    """Worker side: copy one parsed batch into a fresh segment; returns
    its name.  The worker's tracker registration is removed — the PARENT
    owns cleanup (it unlinks on attach, or discard_segment on teardown)."""
    core, meta = _layout(spec)
    fields = core + (meta if has_meta else [])
    shm = shared_memory.SharedMemory(create=True, size=max(1, _nbytes(fields)))
    try:
        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:  # pragma: no cover - tracker impl drift
        pass
    off = 0
    values = {name: getattr(batch, name) for name in _CORE}
    if has_meta:
        values.update(
            {name: getattr(batch.sort_meta, name) for name in _META}
        )
    for name, shape, dt in fields:
        count = int(np.prod(shape))
        dst = np.frombuffer(shm.buf, dt, count=count, offset=off)
        dst[:] = np.ascontiguousarray(values[name], dt).reshape(-1)
        del dst
        off += count * np.dtype(dt).itemsize
    name = shm.name
    shm.close()
    return name


def _adopt_mapping(shm: shared_memory.SharedMemory):
    """Take ownership of the wrapper's mmap and neutralize the wrapper.

    Binding the numpy views straight to the ``mmap`` object makes the
    mapping's lifetime exactly the views' lifetime: the views hold the
    mmap alive through their .base chain, and when the last one dies the
    mmap deallocates (its buffer exports are gone by definition) and the
    kernel reclaims the pages.  The SharedMemory wrapper cannot be left
    to do this — its ``__del__`` calls ``close()``, which raises
    BufferError while views still export the buffer — so its fd is
    closed here (the mapping survives an fd close) and its fields are
    cleared to make that ``__del__`` a no-op.
    """
    mm = shm._mmap
    try:
        shm._buf.release()  # never exported: views come from mm below
    except Exception:  # pragma: no cover - buf impl drift
        pass
    try:
        os.close(shm._fd)
    except OSError:  # pragma: no cover - already closed
        pass
    shm._buf = None
    shm._mmap = None
    shm._fd = -1
    return mm


def attach_batch(spec: WorkerSpec, name: str, has_meta: bool) -> Batch:
    """Parent side: map a shipped segment into zero-copy Batch views.

    The segment is unlinked immediately (pages persist while mapped);
    the mapping frees when the last field view is garbage collected, so
    cached batches keep their pages exactly as long as the cache lives.
    """
    core, meta = _layout(spec)
    fields = core + (meta if has_meta else [])
    shm = shared_memory.SharedMemory(name=name)
    try:
        shm.unlink()
    except FileNotFoundError:  # pragma: no cover - double-teardown race
        pass
    flat = np.frombuffer(_adopt_mapping(shm), np.uint8)
    out = {}
    off = 0
    for name_, shape, dt in fields:
        count = int(np.prod(shape))
        nb = count * np.dtype(dt).itemsize
        out[name_] = flat[off:off + nb].view(dt).reshape(shape)
        off += nb
    sort_meta = (
        SortMeta(*(out[n] for n in _META)) if has_meta else None
    )
    return Batch(*(out[n] for n in _CORE), sort_meta=sort_meta)


def discard_segment(name: str) -> None:
    """Teardown path: unlink a shipped segment that will never be
    attached (its worker already unregistered it from the tracker)."""
    try:
        shm = shared_memory.SharedMemory(name=name)
    except FileNotFoundError:
        return
    try:
        shm.unlink()
    except FileNotFoundError:  # pragma: no cover
        pass
    shm.close()


def put_with_stop(q, item, stop) -> bool:
    """Bounded mp-queue put that gives up once ``stop`` is set — the
    process-pool analogue of ``_ClosableQueue.put`` (an mp.Queue cannot
    be cancelled, so the poll period bounds shutdown latency instead).
    Shared by the pipeline's reader thread and the workers."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except _queue.Full:
            continue
    return False


def _safe_exc(e: BaseException) -> BaseException:
    """An exception guaranteed to survive the result queue's pickling
    (an unpicklable error would be dropped by the feeder thread and the
    failure would vanish)."""
    try:
        pickle.loads(pickle.dumps(e))
        return e
    except Exception:
        return RuntimeError(f"{type(e).__name__}: {e}")


def _build_parser(spec: WorkerSpec):
    """(parse_lines_fn, parse_raw_fn, trunc_fn) for this worker."""
    native_parser = None
    if spec.use_native:
        # The parent parsed natively; a child that silently fell back to
        # the Python oracle could disagree bit-for-bit on edge tokens —
        # fail loudly instead (same container, so this only fires when
        # the build env genuinely changed under us).
        from fast_tffm_tpu.data import native

        native_parser = native.NativeParser(
            vocabulary_size=spec.vocabulary_size,
            max_features=spec.max_features,
            hash_feature_id=spec.hash_feature_id,
            field_num=spec.field_num,
            num_threads=1,
        )

        def parse_lines(lines, weights):
            return native_parser.parse_batch(
                lines, spec.batch_size, weights
            )

        def parse_raw(buf, starts, ends):
            return native_parser.parse_raw(
                buf, starts, ends, spec.batch_size
            )

        def trunc():
            return native_parser.truncated_features

        return parse_lines, parse_raw, trunc

    from fast_tffm_tpu.data import libsvm

    def parse_lines_py(lines, weights):
        examples = libsvm.parse_lines(
            lines, spec.vocabulary_size, spec.hash_feature_id,
            spec.field_num,
        )
        return libsvm.make_batch(
            examples, spec.batch_size, spec.max_features, weights
        )

    def parse_raw_py(buf, starts, ends):  # pragma: no cover - guarded
        raise RuntimeError("raw ingest requires the native parser")

    return parse_lines_py, parse_raw_py, lambda: 0


def parse_worker_main(spec: WorkerSpec, work, out, stop) -> None:
    """Entry point of one spawned parse worker.

    Work messages (from the pipeline's reader thread):
      ("raw",   seq0, buf, [starts...], [ends...])  — one raw WINDOW,
          sliced into len(starts) consecutive groups seq0, seq0+1, ...
          (the window's bytes cross the queue once, not once per group);
      ("lines", seq, lines, weights)                — one line-path chunk;
      ("mark",  seq, epoch)                         — epoch marker, echoed;
      None                                          — shutdown sentinel.

    Result messages:
      ("batch", seq, shm_name, has_meta, trunc_delta, note, parse_s)
      ("mark", seq, epoch) | ("err", exc) | ("done",)

    ``parse_s`` is this batch's parse+prep wall time in the worker — a
    spawned process cannot write to the parent's telemetry registry, so
    the duration rides the result message and the parent observes it
    into the shared ``ingest.parse`` timer.
    """
    parse_lines, parse_raw, trunc = _build_parser(spec)
    meta_spec = spec.sort_meta_spec

    def put(msg) -> bool:
        return put_with_stop(out, msg, stop)

    def emit(batch: Batch, seq: int, trunc_delta: int,
             parse_s: float) -> bool:
        nonlocal meta_spec
        note = None
        has_meta = False
        if meta_spec is not None:
            from fast_tffm_tpu.data import native

            t0 = time.perf_counter()
            try:
                batch = batch._replace(
                    sort_meta=native.sort_meta(batch.ids, *meta_spec)
                )
                has_meta = True
            except native.OutOfRangeIdsError as e:
                note = ("oor", str(e))  # parent warns per bad batch
            except Exception as e:
                meta_spec = None  # this worker degrades for good
                note = ("meta_failed", f"{type(e).__name__}: {e}")
            # sort prep is parse-stage work; fold it into the shipped time
            parse_s += time.perf_counter() - t0
        shm_name = ship_batch(spec, batch, has_meta)
        if put(("batch", seq, shm_name, has_meta, trunc_delta, note,
                parse_s)):
            return True
        # Teardown raced the ship: the segment is already unregistered
        # from this worker's tracker and nobody will ever attach it —
        # unlink here or it outlives the run in /dev/shm.
        discard_segment(shm_name)
        return False

    while not stop.is_set():
        try:
            msg = work.get(timeout=0.1)
        except _queue.Empty:
            continue
        if msg is None:
            put(("done",))
            return
        try:
            kind = msg[0]
            if kind == "mark":
                if not put(msg):
                    return
                continue
            if kind == "raw":
                _, seq0, buf, starts_list, ends_list = msg
                for j, (s, e) in enumerate(zip(starts_list, ends_list)):
                    before = trunc()
                    t0 = time.perf_counter()
                    batch = parse_raw(buf, s, e)
                    dt = time.perf_counter() - t0
                    if not emit(batch, seq0 + j, trunc() - before, dt):
                        return
            else:  # lines
                _, seq, lines, weights = msg
                before = trunc()
                t0 = time.perf_counter()
                batch = parse_lines(lines, weights)
                dt = time.perf_counter() - t0
                if not emit(batch, seq, trunc() - before, dt):
                    return
        except BaseException as e:
            if not put(("err", _safe_exc(e))):
                return
