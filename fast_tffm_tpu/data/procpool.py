"""Multiprocess parse workers: GIL-free ingest parsing.

``parse_processes > 0`` moves batch parsing out of the trainer process
into a pool of SPAWNED workers (never forked: a fork would inherit JAX's
runtime threads and held locks; a spawned child imports only numpy + the
data layer).  This is the rebuild's answer to the reference's free-running
C++ ``FmParser`` threads: the pure-Python parse fallback is GIL-bound no
matter what ``thread_num`` says, and even the ctypes path serializes its
Python-side batch assembly — worker processes sidestep both.

Both directions of the worker queue are shared-memory backed:

- INBOUND (:class:`ShmRing`): the reader writes each raw window's bytes
  (text + line offsets) straight into a slot of one fixed ring segment;
  only a slot DESCRIPTOR (slot id, lengths, group sizes — a few hundred
  bytes) crosses the work queue, and workers parse in place from the
  mapped slot.  The previous design pickled every window's multi-MB
  byte buffer through the queue.  Workers return the slot id on a free
  queue once the window is fully parsed; a window that outgrows the
  slot capacity falls back to the pickled path (counted, never wrong).
- OUTBOUND (``ship_batch``/``attach_batch``): the worker lays the
  parsed batch's contiguous numpy arrays (and, when host sort prep is
  on, the sort_meta arrays — all shapes are static given the config)
  into ONE per-batch segment and ships just the segment name over the
  result queue.  The parent maps the segment and wraps zero-copy views,
  so the only post-parse copy is the super-batch stacking.

Every segment a pipeline creates (the ring and all shipped batches)
carries the pipeline's unique ``shm_tag`` name prefix, so teardown can
sweep ``/dev/shm`` for stragglers — a worker killed between creating a
segment and shipping its name can no longer leak it.

Segment lifecycle (Python 3.10: no ``track=False``):

- the worker creates the segment, UNREGISTERS it from its resource
  tracker (the segment must outlive the worker's queue turnover), writes,
  and closes its own mapping;
- the parent attaches, immediately ``unlink()``\\ s (the name disappears;
  pages persist while mapped) and adopts the raw mmap out of the wrapper
  (``_adopt_mapping``) — the views' .base chain then owns the mapping,
  so the kernel reclaims the pages when the last view dies.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import pickle
import queue as _queue
import time
from multiprocessing import resource_tracker, shared_memory
from typing import Optional

import numpy as np

from fast_tffm_tpu.data.libsvm import Batch, SortMeta

_SHM_DIR = "/dev/shm"
_pipe_ids = itertools.count()
_ship_ids = itertools.count()

# Quality-sketch shipping cadence (WorkerSpec.sketch_every's one
# production value): a serialized SketchSet delta is a few KB, so one
# per batch would undo the ring's descriptor-only queue discipline;
# one per this-many batches amortizes it to noise while keeping the
# parent's windows at most this many batches stale per worker.
SKETCH_SHIP_EVERY = 16


def make_shm_tag() -> str:
    """Unique per-pipeline prefix for every segment the pipeline (or its
    workers) creates — the handle :func:`sweep_segments` cleans up by.
    The trailing delimiter matters: without it, pipeline p1's teardown
    sweep would prefix-match pipeline p10's live segments."""
    return f"tffm{os.getpid()}p{next(_pipe_ids)}_"


@dataclasses.dataclass(frozen=True)
class WorkerSpec:
    """Everything a spawned worker needs to parse (picklable; no FmConfig
    so children never import jax-adjacent modules)."""

    vocabulary_size: int
    max_features: int
    hash_feature_id: bool
    field_num: int
    batch_size: int
    use_native: bool  # parent's parser choice; children must match it
    sort_meta_spec: Optional[tuple]  # (vocab, chunk, tile) or None
    shm_tag: str = "tffm0p0"  # name prefix for all segments of this run
    ring_name: Optional[str] = None  # inbound ShmRing segment (None = off)
    ring_slots: int = 0
    ring_slot_bytes: int = 0
    # Record per-batch/per-window trace spans (obs.Tracer) in the worker
    # and ship them back with each result message; the parent merges
    # them into the run's trace file under this worker's pid lane.
    trace: bool = False
    # Model-quality drift sketches (obs/sketch.py): > 0 means sketch
    # every parsed batch's feature values / lengths / id occupancy into
    # a worker-local SketchSet and ship the serialized DELTA back every
    # this-many batches (reset after each ship; the final remainder
    # rides the trailing "done" message) — the parent merges deltas
    # into the run's StreamSketch, the same channel discipline as the
    # shipped parse timings.  0 = off (no per-batch sketch work).
    sketch_every: int = 0


_CORE = ("labels", "ids", "vals", "fields", "weights")
_META = ("perm", "upos", "lrow_last", "starts", "firsts", "ends",
         "tile_start")


def _layout(spec: WorkerSpec):
    """[(name, shape, dtype)] for the core batch and the sort_meta tail.

    Every shape is static given the spec — n_pad/n_chunks/n_tiles mirror
    native.sort_meta's padding math — so writer and reader agree on the
    segment layout without shipping shapes per batch.
    """
    b, f = spec.batch_size, spec.max_features
    core = [
        ("labels", (b,), np.float32),
        ("ids", (b, f), np.int32),
        ("vals", (b, f), np.float32),
        ("fields", (b, f), np.int32),
        ("weights", (b,), np.float32),
    ]
    meta: list = []
    if spec.sort_meta_spec is not None:
        vocab, chunk, tile = spec.sort_meta_spec
        n = b * f
        n_pad = -(-n // chunk) * chunk
        n_chunks = n_pad // chunk
        n_tiles = vocab // tile
        meta = [
            ("perm", (n_pad,), np.int32),
            ("upos", (n_pad,), np.int32),
            ("lrow_last", (n_pad,), np.float32),
            ("starts", (n_chunks,), np.int32),
            ("firsts", (n_chunks + 1,), np.int32),
            ("ends", (n_chunks,), np.int32),
            ("tile_start", (n_tiles + 1,), np.int32),
        ]
    return core, meta


def _nbytes(fields) -> int:
    return sum(
        int(np.prod(shape)) * np.dtype(dt).itemsize for _, shape, dt in fields
    )


def _pad8(n: int) -> int:
    return (n + 7) & ~7


def _untrack(shm: shared_memory.SharedMemory) -> None:
    """Remove this process's resource-tracker registration for a segment
    whose lifetime someone else owns (the tracker would otherwise unlink
    it when THIS process exits, yanking pages from live users)."""
    try:
        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:  # pragma: no cover - tracker impl drift
        pass


class ShmRing:
    """Inbound shared-memory ring: raw windows, parent → workers.

    One fixed segment of ``slots`` × ``slot_bytes``.  The parent writes a
    window (text bytes, then the 8-aligned int64 starts/ends offset
    arrays) into a free slot and ships only the slot descriptor; workers
    map the same segment at startup, parse straight out of the slot, and
    return the slot id on a free queue.  Free-slot flow control IS the
    ring's backpressure — the reader blocks on the free queue when every
    slot is in flight.

    The creating (parent) process keeps its resource-tracker
    registration while the ring lives, so a hard-killed parent still
    gets the segment unlinked at tracker exit; :meth:`destroy` is the
    clean path (unlink + unregister, idempotent).  Workers attach with
    :meth:`attach` and drop their own tracker registration — the parent
    owns cleanup.
    """

    def __init__(self, shm: shared_memory.SharedMemory, slots: int,
                 slot_bytes: int):
        self._shm = shm
        self.name = shm.name
        self.slots = slots
        self.slot_bytes = slot_bytes

    @classmethod
    def create(cls, tag: str, slots: int, slot_bytes: int) -> "ShmRing":
        shm = shared_memory.SharedMemory(
            create=True, size=max(1, slots * slot_bytes),
            name=f"{tag}ring",
        )
        return cls(shm, slots, slot_bytes)

    @classmethod
    def attach(cls, name: str, slots: int, slot_bytes: int) -> "ShmRing":
        shm = shared_memory.SharedMemory(name=name)
        # No unregister here: spawned workers SHARE the parent's
        # resource-tracker process (the fd rides the spawn handshake)
        # and its cache is a set — an attach's duplicate registration
        # collapses into the parent's entry, so a worker-side
        # unregister would steal that entry and the parent's final
        # unlink would log a tracker KeyError.  The duplicate register
        # is harmless; the entry dies with the parent's unlink.
        return cls(shm, slots, slot_bytes)

    def write(self, slot: int, text, starts: np.ndarray,
              ends: np.ndarray) -> int:
        """Lay one window into ``slot``; returns bytes written.  Layout:
        ``[text][pad to 8][starts int64 x n][ends int64 x n]``."""
        base = slot * self.slot_bytes
        mv = self._shm.buf
        tl = len(text)
        mv[base:base + tl] = text
        off = base + _pad8(tl)
        n = len(starts)
        dst = np.frombuffer(mv, np.int64, count=2 * n, offset=off)
        dst[:n] = starts
        dst[n:] = ends
        del dst  # drop the buffer export before any close()
        return _pad8(tl) + 16 * n

    def read(self, slot: int, text_len: int, n: int):
        """(text_memoryview, starts, ends) zero-copy views of a slot."""
        base = slot * self.slot_bytes
        text = memoryview(self._shm.buf)[base:base + text_len]
        off = base + _pad8(text_len)
        arr = np.frombuffer(self._shm.buf, np.int64, count=2 * n,
                            offset=off)
        return text, arr[:n], arr[n:]

    @staticmethod
    def need_bytes(text_len: int, n_lines: int) -> int:
        return _pad8(text_len) + 16 * n_lines

    def close(self) -> None:
        try:
            self._shm.close()
        except BufferError:  # pragma: no cover - a view still exported
            pass

    def destroy(self) -> None:
        """Parent-side teardown (idempotent): unlink — which also drops
        this process's tracker registration — and close the mapping.  A
        name already gone (swept externally) still needs the tracker
        registration cleared or exit-time cleanup warns about it."""
        try:
            self._shm.unlink()
        except FileNotFoundError:
            _untrack(self._shm)
        self.close()


def sweep_segments(tag: str) -> int:
    """Unlink every /dev/shm segment carrying ``tag`` — the teardown
    backstop for segments a crashed worker created but never shipped
    (and for the ring, had destroy() not run).  Only called after the
    worker pool is reaped, so nothing tagged is still in use.  Returns
    the number of segments removed."""
    removed = 0
    try:
        names = os.listdir(_SHM_DIR)
    except OSError:  # pragma: no cover - non-Linux /dev/shm layout
        return 0
    for name in names:
        if name.startswith(tag):
            try:
                os.unlink(os.path.join(_SHM_DIR, name))
                removed += 1
            except OSError:  # pragma: no cover - raced another cleaner
                pass
    return removed


def ship_batch(spec: WorkerSpec, batch: Batch, has_meta: bool) -> str:
    """Worker side: copy one parsed batch into a fresh segment; returns
    its name.  The worker's tracker registration is removed — the PARENT
    owns cleanup (it unlinks on attach, or discard_segment on teardown).
    Segments carry the run's shm_tag so a crashed worker's orphans are
    still findable by the parent's teardown sweep."""
    core, meta = _layout(spec)
    fields = core + (meta if has_meta else [])
    size = max(1, _nbytes(fields))
    while True:
        name = f"{spec.shm_tag}o{os.getpid()}x{next(_ship_ids)}"
        try:
            shm = shared_memory.SharedMemory(
                create=True, size=size, name=name
            )
            break
        except FileExistsError:  # pragma: no cover - counter collision
            continue
    _untrack(shm)
    off = 0
    values = {name: getattr(batch, name) for name in _CORE}
    if has_meta:
        values.update(
            {name: getattr(batch.sort_meta, name) for name in _META}
        )
    for name, shape, dt in fields:
        count = int(np.prod(shape))
        dst = np.frombuffer(shm.buf, dt, count=count, offset=off)
        dst[:] = np.ascontiguousarray(values[name], dt).reshape(-1)
        del dst
        off += count * np.dtype(dt).itemsize
    name = shm.name
    shm.close()
    return name


def _adopt_mapping(shm: shared_memory.SharedMemory):
    """Take ownership of the wrapper's mmap and neutralize the wrapper.

    Binding the numpy views straight to the ``mmap`` object makes the
    mapping's lifetime exactly the views' lifetime: the views hold the
    mmap alive through their .base chain, and when the last one dies the
    mmap deallocates (its buffer exports are gone by definition) and the
    kernel reclaims the pages.  The SharedMemory wrapper cannot be left
    to do this — its ``__del__`` calls ``close()``, which raises
    BufferError while views still export the buffer — so its fd is
    closed here (the mapping survives an fd close) and its fields are
    cleared to make that ``__del__`` a no-op.
    """
    mm = shm._mmap
    try:
        shm._buf.release()  # never exported: views come from mm below
    except Exception:  # pragma: no cover - buf impl drift
        pass
    try:
        os.close(shm._fd)
    except OSError:  # pragma: no cover - already closed
        pass
    shm._buf = None
    shm._mmap = None
    shm._fd = -1
    return mm


def attach_batch(spec: WorkerSpec, name: str, has_meta: bool) -> Batch:
    """Parent side: map a shipped segment into zero-copy Batch views.

    The segment is unlinked immediately (pages persist while mapped);
    the mapping frees when the last field view is garbage collected, so
    cached batches keep their pages exactly as long as the cache lives.
    """
    core, meta = _layout(spec)
    fields = core + (meta if has_meta else [])
    shm = shared_memory.SharedMemory(name=name)
    try:
        shm.unlink()
    except FileNotFoundError:  # pragma: no cover - double-teardown race
        pass
    flat = np.frombuffer(_adopt_mapping(shm), np.uint8)
    out = {}
    off = 0
    for name_, shape, dt in fields:
        count = int(np.prod(shape))
        nb = count * np.dtype(dt).itemsize
        out[name_] = flat[off:off + nb].view(dt).reshape(shape)
        off += nb
    sort_meta = (
        SortMeta(*(out[n] for n in _META)) if has_meta else None
    )
    return Batch(*(out[n] for n in _CORE), sort_meta=sort_meta)


def discard_segment(name: str) -> None:
    """Teardown path: unlink a shipped segment that will never be
    attached (its worker already unregistered it from the tracker)."""
    try:
        shm = shared_memory.SharedMemory(name=name)
    except FileNotFoundError:
        return
    try:
        shm.unlink()
    except FileNotFoundError:  # pragma: no cover
        pass
    shm.close()


def put_with_stop(q, item, stop) -> bool:
    """Bounded mp-queue put that gives up once ``stop`` is set — the
    process-pool analogue of ``_ClosableQueue.put`` (an mp.Queue cannot
    be cancelled, so the poll period bounds shutdown latency instead).
    Shared by the pipeline's reader thread and the workers."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except _queue.Full:
            continue
    return False


def get_with_stop(q, stop):
    """Blocking mp-queue get that gives up (returns None) once ``stop``
    is set — used by the reader waiting for a free ring slot."""
    while not stop.is_set():
        try:
            return q.get(timeout=0.1)
        except _queue.Empty:
            continue
    return None


def _safe_exc(e: BaseException) -> BaseException:
    """An exception guaranteed to survive the result queue's pickling
    (an unpicklable error would be dropped by the feeder thread and the
    failure would vanish)."""
    try:
        pickle.loads(pickle.dumps(e))
        return e
    except Exception:
        return RuntimeError(f"{type(e).__name__}: {e}")


def _build_parser(spec: WorkerSpec):
    """(parse_lines_fn, parse_raw_fn, trunc_fn) for this worker."""
    native_parser = None
    if spec.use_native:
        # The parent parsed natively; a child that silently fell back to
        # the Python oracle could disagree bit-for-bit on edge tokens —
        # fail loudly instead (same container, so this only fires when
        # the build env genuinely changed under us).
        from fast_tffm_tpu.data import native

        native_parser = native.NativeParser(
            vocabulary_size=spec.vocabulary_size,
            max_features=spec.max_features,
            hash_feature_id=spec.hash_feature_id,
            field_num=spec.field_num,
            num_threads=1,
        )

        def parse_lines(lines, weights):
            return native_parser.parse_batch(
                lines, spec.batch_size, weights
            )

        def parse_raw(buf, starts, ends):
            return native_parser.parse_raw(
                buf, starts, ends, spec.batch_size
            )

        def trunc():
            return native_parser.truncated_features

        return parse_lines, parse_raw, trunc

    from fast_tffm_tpu.data import libsvm

    def parse_lines_py(lines, weights):
        examples = libsvm.parse_lines(
            lines, spec.vocabulary_size, spec.hash_feature_id,
            spec.field_num,
        )
        return libsvm.make_batch(
            examples, spec.batch_size, spec.max_features, weights
        )

    def parse_raw_py(buf, starts, ends):  # pragma: no cover - guarded
        raise RuntimeError("raw ingest requires the native parser")

    return parse_lines_py, parse_raw_py, lambda: 0


def parse_worker_main(spec: WorkerSpec, work, out, stop,
                      ring_free=None) -> None:
    """Entry point of one spawned parse worker.

    Work messages (from the pipeline's reader thread):
      ("rawslot", seq0, slot, text_len, [n_lines...]) — one raw WINDOW
          already resident in the shared-memory ring (spec.ring_name):
          the descriptor names the slot and the per-group line counts;
          the worker parses IN PLACE from the mapped slot and returns
          the slot id on ``ring_free`` when the window is done — no
          window bytes ever cross the queue;
      ("raw",   seq0, buf, [starts...], [ends...])  — pickled-window
          fallback (ring off, or a window larger than a ring slot);
      ("lines", seq, lines, weights)                — one line-path chunk;
      ("mark",  seq, epoch)                         — epoch marker, echoed;
      None                                          — shutdown sentinel.

    Result messages:
      ("batch", seq, shm_name, has_meta, trunc_delta, note, parse_s,
       spans, sketch_delta)
      ("mark", seq, epoch) | ("err", exc) | ("done", spans,
       sketch_delta)

    ``parse_s`` is this batch's parse+prep wall time in the worker — a
    spawned process cannot write to the parent's telemetry registry, so
    the duration rides the result message and the parent observes it
    into the shared ``ingest.parse`` timer.  ``spans`` works the same
    way for the trace layer: with ``spec.trace`` the worker records
    Chrome-trace events (``parse.batch`` per batch, ``parse.window`` per
    ring window — its end marks the slot release) into a local
    obs.Tracer and ships the accumulated raw events with each result;
    the parent merges them into the run's trace under this worker's pid.
    ``sketch_delta`` (``spec.sketch_every > 0``) is the quality plane's
    version of the same contract: the worker folds each parsed batch
    into a local ``obs.sketch.SketchSet`` and ships the serialized
    delta every ``sketch_every`` batches (None in between; the sketch
    resets at each ship so the parent absorbs every delta exactly
    once).  The trailing ``("done", spans, sketch_delta)`` flushes
    spans that ended after the last batch shipped (the final window
    span) and the sketch remainder.
    """
    parse_lines, parse_raw, trunc = _build_parser(spec)
    from fast_tffm_tpu.obs.trace import Tracer

    tracer = Tracer(
        enabled=spec.trace, process_name=f"parse-worker {os.getpid()}"
    )
    sketch = None
    sketch_pending = 0
    if spec.sketch_every > 0:
        from fast_tffm_tpu.obs.sketch import SketchSet

        sketch = SketchSet()
    meta_spec = spec.sort_meta_spec
    ring = None
    if spec.ring_name is not None:
        ring = ShmRing.attach(
            spec.ring_name, spec.ring_slots, spec.ring_slot_bytes
        )

    def put(msg) -> bool:
        return put_with_stop(out, msg, stop)

    def emit(batch: Batch, seq: int, trunc_delta: int,
             parse_s: float) -> bool:
        nonlocal meta_spec, sketch, sketch_pending
        note = None
        has_meta = False
        if meta_spec is not None:
            from fast_tffm_tpu.data import native

            t0 = time.perf_counter()
            try:
                batch = batch._replace(
                    sort_meta=native.sort_meta(batch.ids, *meta_spec)
                )
                has_meta = True
            except native.OutOfRangeIdsError as e:
                note = ("oor", str(e))  # parent warns per bad batch
            except Exception as e:
                meta_spec = None  # this worker degrades for good
                note = ("meta_failed", f"{type(e).__name__}: {e}")
            # sort prep is parse-stage work; fold it into the shipped time
            parse_s += time.perf_counter() - t0
        delta = None
        if sketch is not None:
            # Guarded like the thread path: sketching is observe-only,
            # so a failure degrades this worker's quality feed (note
            # shipped once; the parent warns) — it must never become
            # an ("err", ...) that kills the run.
            try:
                sketch.update_batch(
                    batch.ids, batch.vals, batch.weights
                )
                sketch_pending += 1
                if sketch_pending >= spec.sketch_every:
                    from fast_tffm_tpu.obs.sketch import SketchSet

                    delta = sketch.to_dict()
                    sketch = SketchSet()
                    sketch_pending = 0
            except Exception as e:  # noqa: BLE001 - observe only
                sketch = None  # this worker degrades for good
                if note is None:
                    note = ("sketch_failed",
                            f"{type(e).__name__}: {e}")
        shm_name = ship_batch(spec, batch, has_meta)
        if put(("batch", seq, shm_name, has_meta, trunc_delta, note,
                parse_s, tracer.take(), delta)):
            return True
        # Teardown raced the ship: the segment is already unregistered
        # from this worker's tracker and nobody will ever attach it —
        # unlink here or it outlives the run in /dev/shm.
        discard_segment(shm_name)
        return False

    while not stop.is_set():
        try:
            msg = work.get(timeout=0.1)
        except _queue.Empty:
            continue
        if msg is None:
            put((
                "done", tracer.take(),
                sketch.to_dict()
                if sketch is not None and sketch_pending else None,
            ))
            return
        try:
            kind = msg[0]
            if kind == "mark":
                if not put(msg):
                    return
                continue
            if kind == "rawslot":
                # Zero-copy window: parse straight out of the mapped
                # ring slot, then hand the slot back for reuse.
                _, seq0, slot, text_len, sizes = msg
                buf, starts, ends = ring.read(slot, text_len, sum(sizes))
                t_w0 = time.perf_counter()
                try:
                    pos = 0
                    for j, n in enumerate(sizes):
                        before = trunc()
                        t0 = time.perf_counter()
                        batch = parse_raw(
                            buf, starts[pos:pos + n], ends[pos:pos + n]
                        )
                        dt = time.perf_counter() - t0
                        tracer.emit("parse.batch", t0, dt,
                                    args={"seq": seq0 + j})
                        pos += n
                        if not emit(batch, seq0 + j, trunc() - before, dt):
                            return
                finally:
                    del buf, starts, ends  # drop the slot's buffer exports
                    ring_free.put(slot)
                    # The window span closes at slot release: its end IS
                    # the moment the slot went back on the free queue.
                    tracer.emit(
                        "parse.window", t_w0,
                        time.perf_counter() - t_w0,
                        args={"slot": slot, "seq0": seq0,
                              "n_batches": len(sizes)},
                    )
            elif kind == "raw":
                _, seq0, buf, starts_list, ends_list = msg
                for j, (s, e) in enumerate(zip(starts_list, ends_list)):
                    before = trunc()
                    t0 = time.perf_counter()
                    batch = parse_raw(buf, s, e)
                    dt = time.perf_counter() - t0
                    tracer.emit("parse.batch", t0, dt,
                                args={"seq": seq0 + j})
                    if not emit(batch, seq0 + j, trunc() - before, dt):
                        return
            else:  # lines
                _, seq, lines, weights = msg
                before = trunc()
                t0 = time.perf_counter()
                batch = parse_lines(lines, weights)
                dt = time.perf_counter() - t0
                tracer.emit("parse.batch", t0, dt, args={"seq": seq})
                if not emit(batch, seq, trunc() - before, dt):
                    return
        except BaseException as e:
            if not put(("err", _safe_exc(e))):
                return
