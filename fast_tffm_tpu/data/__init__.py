from fast_tffm_tpu.data.libsvm import (  # noqa: F401
    Batch,
    make_batch,
    murmur64,
    parse_line,
    parse_lines,
)
