"""libsvm/ffm text parsing — pure-Python oracle for the C++ parser.

The reference parses batches of libsvm lines (``label feat:val ...``) in a
multi-threaded C++ TF op (``FmParser``, SURVEY.md §2 #1), optionally hashing
arbitrary feature-id strings into ``vocabulary_size`` buckets.  This module
is the bit-exact Python oracle: the C++ extension
(``fast_tffm_tpu/data/_src/fm_parser.cc``) must agree with it on every line,
and tests enforce that.

Unlike the reference's ragged tensors, batches here are **padded to a static
shape** ``[batch, max_features]`` — XLA requires static shapes, and padded
slots carry ``val == 0`` so they contribute nothing to the FM score or its
gradient (score terms and grads are all scaled by the feature value).

Supported line formats:
  - libsvm:  ``label id:val id:val ...``
  - ffm:     ``label field:id:val ...`` (field-aware FM extension)
  - ids are integers, or arbitrary strings when ``hash_feature_id`` is on.
"""

from __future__ import annotations

import re
from typing import Iterable, NamedTuple, Optional, Sequence

import numpy as np

# Strict numeric token grammar, shared spec with the C++ parser: plain
# Python float()/int() accept forms C parsing rejects (underscore
# literals "1_0", Unicode digits), and C's strtof accepts forms Python
# rejects (hex floats "0x10", nan payloads "nan(x)").  Both sides pin to
# the ASCII intersection; a round-4 fuzz (test_native_parser) found the
# divergences.
_FLOAT_RE = re.compile(
    r"[+-]?(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?|[+-]?(?:inf(?:inity)?|nan)",
    re.IGNORECASE | re.ASCII,
)
_INT_RE = re.compile(r"[+-]?\d+", re.ASCII)


def _strict_float(token: str) -> float:
    if not _FLOAT_RE.fullmatch(token):
        raise ValueError(f"could not convert string to float: {token!r}")
    return float(token)


def _strict_int(token: str) -> int:
    if not _INT_RE.fullmatch(token):
        raise ValueError(f"invalid literal for int(): {token!r}")
    return int(token)

_MASK64 = (1 << 64) - 1
_M = 0xC6A4A7935BD1E995
_R = 47


def murmur64(data: bytes, seed: int = 0) -> int:
    """MurmurHash64A — matches the C++ implementation bit-for-bit."""
    length = len(data)
    h = (seed ^ ((length * _M) & _MASK64)) & _MASK64
    n_blocks = length // 8
    for i in range(n_blocks):
        k = int.from_bytes(data[i * 8 : i * 8 + 8], "little")
        k = (k * _M) & _MASK64
        k ^= k >> _R
        k = (k * _M) & _MASK64
        h ^= k
        h = (h * _M) & _MASK64
    tail = data[n_blocks * 8 :]
    if tail:
        t = int.from_bytes(tail, "little")
        h ^= t
        h = (h * _M) & _MASK64
    h ^= h >> _R
    h = (h * _M) & _MASK64
    h ^= h >> _R
    return h


def hash_bucket(token: str, vocabulary_size: int) -> int:
    return murmur64(token.encode("utf-8")) % vocabulary_size


class SortMeta(NamedTuple):
    """Host-precomputed sparse-apply prep (see native.sort_meta).

    Everything ops/sparse_apply derives from the batch's ids alone —
    stable sort permutation, unique positions, chunk/tile boundary
    metadata — computed by the C++ layer on pipeline threads so the
    device step skips its ~11 ms XLA sort (+ boundary searches).  All
    shapes depend on (CHUNK, TILE, vocab), which the producer and the
    kernels must agree on; sparse_apply verifies at trace time.
    """

    perm: np.ndarray  # [n_pad] i32 occurrence index per sorted position
    upos: np.ndarray  # [n_pad] i32 unique-segment index per sorted pos
    lrow_last: np.ndarray  # [n_pad] f32 (id % TILE) at segment ends
    starts: np.ndarray  # [n_pad/CHUNK] i32 upos at chunk starts
    firsts: np.ndarray  # [n_pad/CHUNK + 1] i32 seg-start flag at chunks
    ends: np.ndarray  # [n_pad/CHUNK] i32 upos at chunk ends
    tile_start: np.ndarray  # [vocab/TILE + 1] i32


class Batch(NamedTuple):
    """A fixed-shape parsed batch, ready for the device.

    Padded feature slots have ``vals == 0`` (and ``ids == 0``), which makes
    them mathematically inert in the FM score and gradient.
    """

    labels: np.ndarray  # [B] float32, in {0, 1} for logistic loss
    ids: np.ndarray  # [B, F] int32 bucket ids
    vals: np.ndarray  # [B, F] float32 feature values (0 = padding)
    fields: np.ndarray  # [B, F] int32 field ids (all 0 for plain FM)
    weights: np.ndarray  # [B] float32 per-example weights
    sort_meta: Optional[SortMeta] = None  # host prep for the tile apply


class Example(NamedTuple):
    label: float
    ids: list[int]
    vals: list[float]
    fields: list[int]


def parse_line(
    line: str,
    vocabulary_size: int,
    hash_feature_id: bool = False,
    field_num: int = 0,
) -> Optional[Example]:
    """Parse one libsvm/ffm line. Returns None for blank/comment lines."""
    line = line.strip()
    if not line or line.startswith("#"):
        return None
    parts = line.split()
    label = _strict_float(parts[0])
    # The reference trains logistic loss on CTR labels; accept {-1,1} and
    # {0,1} conventions by folding -1 to 0.
    if label == -1.0:
        label = 0.0
    ids: list[int] = []
    vals: list[float] = []
    fields: list[int] = []
    for tok in parts[1:]:
        pieces = tok.split(":")
        if len(pieces) == 3:
            field_s, id_s, val_s = pieces
            field = _strict_int(field_s)
        elif len(pieces) == 2:
            field = 0
            id_s, val_s = pieces
        elif len(pieces) == 1:
            # Bare feature id => implicit value 1.0 (binary features).
            field, id_s, val_s = 0, pieces[0], "1"
        else:
            raise ValueError(f"malformed feature token {tok!r}")
        if hash_feature_id:
            fid = hash_bucket(id_s, vocabulary_size)
        else:
            fid = _strict_int(id_s) % vocabulary_size
        if field_num:
            field = field % field_num
        ids.append(fid)
        vals.append(_strict_float(val_s))
        fields.append(field)
    return Example(label, ids, vals, fields)


def parse_lines(
    lines: Iterable[str],
    vocabulary_size: int,
    hash_feature_id: bool = False,
    field_num: int = 0,
) -> list[Example]:
    out = []
    for line in lines:
        ex = parse_line(line, vocabulary_size, hash_feature_id, field_num)
        if ex is not None:
            out.append(ex)
    return out


def make_batch(
    examples: Sequence[Example],
    batch_size: int,
    max_features: int,
    weights: Optional[Sequence[float]] = None,
) -> Batch:
    """Pad/truncate examples into a static-shape Batch.

    Short batches (end of epoch) are padded with weight-0 examples so the
    device shapes never change; truncated features beyond ``max_features``
    are dropped (the C++ parser counts these so callers can warn).
    """
    n = len(examples)
    if n > batch_size:
        raise ValueError(f"{n} examples > batch_size {batch_size}")
    labels = np.zeros((batch_size,), np.float32)
    ids = np.zeros((batch_size, max_features), np.int32)
    vals = np.zeros((batch_size, max_features), np.float32)
    fields = np.zeros((batch_size, max_features), np.int32)
    w = np.zeros((batch_size,), np.float32)
    for i, ex in enumerate(examples):
        labels[i] = ex.label
        k = min(len(ex.ids), max_features)
        ids[i, :k] = ex.ids[:k]
        vals[i, :k] = ex.vals[:k]
        fields[i, :k] = ex.fields[:k]
        w[i] = 1.0 if weights is None else weights[i]
    return Batch(labels, ids, vals, fields, w)
