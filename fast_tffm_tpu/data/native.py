"""ctypes wrapper around the C++ batch parser (lazy-built with g++).

The shared library is compiled on first use into the package directory and
cached (rebuilt when the source is newer).  ``NativeParser.parse_batch`` is
the drop-in fast path for the Python oracle's parse+batch
(:func:`fast_tffm_tpu.data.libsvm.parse_lines` + ``make_batch``); tests
enforce bit-exact agreement between the two.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional, Sequence

import numpy as np

from fast_tffm_tpu.data.libsvm import Batch

log = logging.getLogger(__name__)


class OutOfRangeIdsError(ValueError):
    """Batch ids fall outside [0, vocabulary_size).

    This is a data / vocabulary_size integrity bug, not a transient
    native failure: the device-sort fallback would silently drop updates
    for the out-of-range ids, so callers must keep surfacing it instead
    of degrading once and going quiet (ADVICE r5).
    """


_SRC_DIR = os.path.join(os.path.dirname(__file__), "_src")
_SRC = os.path.join(_SRC_DIR, "fm_parser.cc")
_LIB = os.path.join(_SRC_DIR, "libfm_parser.so")
_build_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


def _build() -> str:
    with _build_lock:
        if os.path.exists(_LIB) and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC):
            return _LIB
        # Plain -O3, no -march=native: measured FASTER here (819k vs 705k
        # lines/s — native's wider vectorization loses on this workload),
        # and a baseline-ISA .so stays safe if the built artifact ever
        # moves to a different CPU (the mtime cache can't tell).
        cmd = [
            "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
            _SRC, "-o", _LIB + ".tmp",
        ]
        log.info("building native parser: %s", " ".join(cmd))
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(_LIB + ".tmp", _LIB)
        return _LIB


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(_build())
    lib.fm_parser_create.restype = ctypes.c_void_p
    lib.fm_parser_create.argtypes = [
        ctypes.c_uint64, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
    ]
    lib.fm_parser_destroy.argtypes = [ctypes.c_void_p]
    lib.fm_parser_parse.restype = ctypes.c_int64
    lib.fm_parser_parse.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ctypes.c_int64,
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        ctypes.c_void_p,
    ]
    lib.fm_parser_murmur64.restype = ctypes.c_uint64
    lib.fm_parser_murmur64.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.fm_parser_find_lines.restype = ctypes.c_int64
    lib.fm_parser_find_lines.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ctypes.c_int64,
    ]
    lib.fm_parser_parse_raw.restype = ctypes.c_int64
    lib.fm_parser_parse_raw.argtypes = [
        ctypes.c_void_p,
        # buf: void* instead of char* so callers can pass either bytes
        # or a raw address into a shared-memory ring slot (ctypes
        # converts bytes to a pointer for c_void_p params too).
        ctypes.c_void_p,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),  # starts
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),  # ends
        ctypes.c_int64,
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        ctypes.c_void_p,
    ]
    lib.fm_sort_meta.restype = ctypes.c_int64
    lib.fm_sort_meta.argtypes = [
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),  # ids
        ctypes.c_int64,  # n
        ctypes.c_int64,  # n_pad
        ctypes.c_int64,  # vocab
        ctypes.c_int64,  # chunk
        ctypes.c_int64,  # tile
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),  # perm
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),  # upos
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),  # starts
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),  # firsts
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),  # ends
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),  # tile_start
    ]
    _lib = lib
    return lib


def sort_meta(ids, vocab: int, chunk: int, tile: int):
    """Host-side sparse-apply prep for one batch's flat ids.

    Mirrors ops/sparse_apply._prep's id-derived outputs exactly (stable
    sort, sentinel padding to a CHUNK multiple); parity is test-enforced.
    Returns a :class:`fast_tffm_tpu.data.libsvm.SortMeta`.
    """
    from fast_tffm_tpu.data.libsvm import SortMeta

    lib = _load()
    ids = np.ascontiguousarray(np.asarray(ids).reshape(-1), np.int32)
    n = ids.shape[0]
    if n:
        # The C++ side also rejects out-of-range ids (it would corrupt
        # its bucket scatter) but folds them into the same rc as bad
        # arguments; pre-checking here gives the caller a typed error to
        # tell the integrity bug apart from a transient failure.
        lo, hi = int(ids.min()), int(ids.max())
        if lo < 0 or hi >= vocab:
            raise OutOfRangeIdsError(
                f"out-of-range batch ids (outside [0, {vocab})): "
                f"min={lo} max={hi} — input data and vocabulary_size "
                "disagree"
            )
    n_pad = -(-n // chunk) * chunk
    n_chunks = n_pad // chunk
    n_tiles = vocab // tile
    perm = np.empty((n_pad,), np.int32)
    upos = np.empty((n_pad,), np.int32)
    lrow_last = np.empty((n_pad,), np.float32)
    starts = np.empty((n_chunks,), np.int32)
    firsts = np.empty((n_chunks + 1,), np.int32)
    ends = np.empty((n_chunks,), np.int32)
    tile_start = np.empty((n_tiles + 1,), np.int32)
    rc = lib.fm_sort_meta(
        ids, n, n_pad, vocab, chunk, tile,
        perm, upos, lrow_last, starts, firsts, ends, tile_start,
    )
    if rc < 0:
        raise ValueError(
            f"fm_sort_meta rejected arguments or out-of-range ids: n={n} "
            f"vocab={vocab} chunk={chunk} tile={tile}"
        )
    return SortMeta(perm, upos, lrow_last, starts, firsts, ends, tile_start)


def find_line_offsets(
    buf: bytes, length: Optional[int] = None, guess: Optional[int] = None
) -> np.ndarray:
    """Line-start offsets in buf[:length] (C++ memchr scan, no copies).

    ``guess`` is the expected line count (callers streaming a file pass the
    previous buffer's count — line density is stable, avoiding a rescan).
    """
    lib = _load()
    n_len = len(buf) if length is None else length
    guess = max(16, n_len // 64 if guess is None else guess)
    while True:
        out = np.empty((guess,), np.int64)
        n = lib.fm_parser_find_lines(buf, n_len, out, guess)
        if n <= guess:
            return out[:n]
        guess = n


def murmur64_native(data: bytes) -> int:
    return _load().fm_parser_murmur64(data, len(data))


class NativeParser:
    """Multi-threaded libsvm batch parser backed by the C++ extension."""

    def __init__(
        self,
        vocabulary_size: int,
        max_features: int,
        hash_feature_id: bool = False,
        field_num: int = 0,
        num_threads: int = 4,
    ):
        self._lib = _load()
        self.max_features = max_features
        self.truncated_features = 0  # running count, like reference warnings
        self._trunc_lock = threading.Lock()  # parser threads share self
        self._handle = self._lib.fm_parser_create(
            vocabulary_size, max_features, int(hash_feature_id), field_num,
            num_threads,
        )
        if not self._handle:
            raise ValueError(
                f"vocabulary_size {vocabulary_size} out of range (must be "
                "in [1, 2^59) for the native parser)"
            )

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.fm_parser_destroy(handle)
            self._handle = None

    def parse_batch(
        self,
        lines: Sequence[str],
        batch_size: int,
        weights: Optional[Sequence[float]] = None,
    ) -> Batch:
        n = len(lines)
        if n > batch_size:
            raise ValueError(f"{n} lines > batch_size {batch_size}")
        encoded = [s.encode("utf-8") for s in lines]
        buf = b"\n".join(encoded)
        lens = np.fromiter((len(e) for e in encoded), np.int64, count=n)
        offsets = np.zeros((n + 1,), np.int64)
        np.cumsum(lens + 1, out=offsets[1:])  # +1 for the joining '\n'
        if n:
            offsets[n] -= 1  # last line has no trailing separator

        labels = np.zeros((batch_size,), np.float32)
        ids = np.zeros((batch_size, self.max_features), np.int32)
        vals = np.zeros((batch_size, self.max_features), np.float32)
        fields = np.zeros((batch_size, self.max_features), np.int32)
        w = np.zeros((batch_size,), np.float32)

        weights_in = None
        weights_ptr = None
        if weights is not None:
            weights_in = np.ascontiguousarray(weights, np.float32)
            if weights_in.shape != (n,):
                raise ValueError("weights must have one entry per line")
            weights_ptr = weights_in.ctypes.data_as(ctypes.c_void_p)

        dropped = self._lib.fm_parser_parse(
            self._handle, buf, offsets, n, labels, ids, vals, fields, w,
            weights_ptr,
        )
        if dropped < 0:
            bad = -int(dropped) - 1
            raise ValueError(
                f"malformed libsvm input at batch line {bad}: {lines[bad]!r}"
            )
        if dropped:
            with self._trunc_lock:
                self.truncated_features += int(dropped)
        return Batch(labels, ids, vals, fields, w)

    def parse_raw(
        self,
        buf: bytes,
        starts: np.ndarray,  # [n] int64 line-start offsets into buf
        ends: np.ndarray,  # [n] int64 line-end offsets (exclusive)
        batch_size: int,
    ) -> Batch:
        """Zero-copy fast path: parse lines straight out of a raw file
        chunk (no Python string per line).  Lines may be non-contiguous
        and in any order — the pipeline's line-level shuffle passes a
        permuted view of a scanned window.  Blank/comment lines become
        weight-0 rows.

        ``buf`` may be bytes or a buffer (a memoryview of a shared-
        memory ring slot): parse workers read straight out of the
        mapped segment, no bytes() copy."""
        buf_arg = buf
        holder = None
        if not isinstance(buf, (bytes, bytearray)):
            # Pass non-bytes buffers by raw address (the argtype is
            # void*).  A numpy view — not a ctypes from_buffer/cast
            # pair, whose internal _objects cycle keeps the buffer
            # exported until a cycle collection and makes the segment's
            # mmap unclosable at worker exit — pins the exporter for
            # the call's duration.
            holder = np.frombuffer(buf, np.uint8)
            buf_arg = holder.ctypes.data
        n = len(starts)
        if n > batch_size:
            raise ValueError(f"{n} lines > batch_size {batch_size}")
        if len(ends) != n:
            raise ValueError(f"starts/ends length mismatch: {n}/{len(ends)}")
        starts = np.ascontiguousarray(starts, np.int64)
        ends = np.ascontiguousarray(ends, np.int64)
        labels = np.zeros((batch_size,), np.float32)
        ids = np.zeros((batch_size, self.max_features), np.int32)
        vals = np.zeros((batch_size, self.max_features), np.float32)
        fields = np.zeros((batch_size, self.max_features), np.int32)
        w = np.zeros((batch_size,), np.float32)
        dropped = self._lib.fm_parser_parse_raw(
            self._handle, buf_arg, starts, ends, n, labels, ids, vals,
            fields, w, None,
        )
        if dropped < 0:
            bad = -int(dropped) - 1
            text = bytes(buf[starts[bad]:ends[bad]])
            raise ValueError(
                f"malformed libsvm input at chunk line {bad}: {text!r}"
            )
        if dropped:
            with self._trunc_lock:
                self.truncated_features += int(dropped)
        return Batch(labels, ids, vals, fields, w)
