"""CLI: ``run_tffm.py {train|predict|serve} <cfg>`` (reference surface,
SURVEY.md §2 #12, plus the online-serving mode).

Local mode mirrors the reference exactly.  Distributed mode replaces the
parameter-server flags with a JAX multi-host launch: every process runs the
same command with ``--coordinator/--num_processes/--process_id`` and GSPMD
shards one global training step over all chips (SURVEY.md §2 #10 — the PS
runtime is subsumed by jit+sharding).

The reference's ``--ps_hosts/--worker_hosts/--job_name/--task_index`` flags
are still accepted so old launch scripts keep working: worker tasks map to
JAX processes; ps tasks exit immediately (there are no parameter servers —
the table is row-sharded across the same chips doing compute).
"""

from __future__ import annotations

import argparse
import logging
import sys

log = logging.getLogger("fast_tffm_tpu")


def _setup_logging(log_file: str | None):
    handlers = [logging.StreamHandler(sys.stderr)]
    if log_file:
        handlers.append(logging.FileHandler(log_file))
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
        handlers=handlers,
        force=True,
    )


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="run_tffm",
        description="TPU-native factorization machine trainer",
    )
    p.add_argument("mode", choices=["train", "predict", "serve"])
    p.add_argument("cfg", help="INI config file (reference-compatible)")
    # TPU-native distributed flags.
    p.add_argument("--coordinator", default=None,
                   help="host:port of process 0 (multi-host)")
    p.add_argument("--num_processes", type=int, default=None)
    p.add_argument("--process_id", type=int, default=None)
    # Dispatch/transfer knobs (override the cfg file; the INI keys of the
    # same name are the durable spelling).
    p.add_argument(
        "--steps_per_dispatch", type=int, default=None,
        help="train K batches per device dispatch via a fused lax.scan "
             "(1 = classic per-batch dispatch)",
    )
    p.add_argument(
        "--prefetch_super_batches", type=int, default=None,
        help="stacked super-batches the transfer stage keeps in flight",
    )
    # Ingest knobs (override the cfg file).
    p.add_argument(
        "--parse_processes", type=int, default=None,
        help="parse in this many spawned worker processes (GIL-free) "
             "instead of thread_num in-process threads (0 = threads)",
    )
    p.add_argument(
        "--cache_epochs", action="store_true", default=None,
        help="parse epoch 0 once and replay later epochs from a host-"
             "memory batch cache (multi-epoch runs whose parsed data "
             "fits in cache_max_bytes)",
    )
    p.add_argument(
        "--cache_max_bytes", type=int, default=None,
        help="byte budget for the epoch cache; overflowing falls back "
             "to re-parsing later epochs",
    )
    p.add_argument(
        "--cache_prestacked", action="store_true", default=None,
        help="store the epoch cache as pre-stacked [K, ...] super-"
             "batches (stacked once; replay epochs skip the transfer "
             "stage's per-dispatch stack) — requires --cache_epochs",
    )
    p.add_argument(
        "--ring_slots", type=int, default=None,
        help="inbound shared-memory ring slots for parse_processes "
             "(raw windows parsed in place; 0 = pickle windows over "
             "the worker queue)",
    )
    # Tiered embedding table knobs (override the cfg file).
    p.add_argument(
        "--table_tiering", choices=["off", "on"], default=None,
        help="two-tier embedding table: device-resident hot rows over a "
             "host-RAM cold store holding the full vocabulary (unlocks "
             "V >= 2^28; requires the sparse update path)",
    )
    p.add_argument(
        "--hot_rows", type=int, default=None,
        help="device-resident rows when --table_tiering on (must cover "
             "one super-batch's unique ids)",
    )
    # Quantized-table knobs (override the cfg file; see ops/quant.py).
    p.add_argument(
        "--cold_dtype", choices=["fp32", "bf16", "int8"], default=None,
        help="storage dtype of the tiered cold store's rows (requires "
             "--table_tiering on): bf16 halves / int8 quarters host "
             "bytes per cold row; training stays f32 on the hot table "
             "and scores within a pinned tolerance of fp32",
    )
    p.add_argument(
        "--serve_table_dtype", choices=["fp32", "bf16", "int8"],
        default=None,
        help="device-resident serving-table dtype (serve mode + "
             "offline predict): quantized tables hold 2-4x more rows "
             "per byte with dequant fused into the compiled rungs "
             "(steady-state still compiles nothing)",
    )
    p.add_argument(
        "--quant_chunk", type=int, default=None,
        help="int8 scale granularity for dense quantized tables: this "
             "many consecutive rows share one fp32 scale (0 = one "
             "scale per row)",
    )
    # Observability knobs (override the cfg file).
    p.add_argument(
        "--heartbeat_secs", type=float, default=None,
        help="emit a structured telemetry heartbeat (JSONL record into "
             "metrics_file + one-line log summary with ingest_wait_frac) "
             "every N seconds (0 = off)",
    )
    p.add_argument(
        "--no_telemetry", action="store_true",
        help="disable the run-wide telemetry layer entirely (no-op "
             "instruments; heartbeats report nothing)",
    )
    p.add_argument(
        "--trace", nargs="?", const="./tffm_trace.json", default=None,
        metavar="PATH", dest="trace_file",
        help="record a Chrome-trace (Perfetto-loadable) span file of "
             "every pipeline stage, correlated per batch/super-batch "
             "(default path ./tffm_trace.json; merge multi-rank files "
             "with tools/report.py --trace)",
    )
    p.add_argument(
        "--nan_policy", choices=["warn", "halt"], default=None,
        help="on a non-finite (NaN/inf) gradient: warn and keep "
             "counting, or halt without overwriting the checkpoint",
    )
    p.add_argument(
        "--status_port", type=int, default=None,
        help="serve a live status endpoint on this port: /metrics "
             "(Prometheus text of every telemetry snapshot + "
             "health/tiered blocks) and /status (the heartbeat JSON "
             "record on demand); read-only, off the hot path (0 = off)",
    )
    p.add_argument(
        "--status_host", default=None, metavar="ADDR",
        help="bind address for --status_port (default 127.0.0.1; the "
             "endpoint is unauthenticated, so 0.0.0.0 — serving a "
             "remote Prometheus — is an explicit opt-in)",
    )
    p.add_argument(
        "--alert_rules", default=None, metavar="RULES",
        help="alert watchdog rules riding the heartbeat, e.g. "
             "'ingest_wait_frac > 0.5 for 3 : warn; "
             "grad_norm_drift > 10 : halt' — breaches emit "
             "`record: alert` JSONL entries; halt stops the run "
             "without overwriting the checkpoint",
    )
    p.add_argument(
        "--train_fleet_scrape", default=None, metavar="H:P,H:P,...",
        help="live training-fleet plane: each rank's status endpoint "
             "(host:port, rank order); rank 0 scrapes every rank's "
             "/status on the heartbeat cadence into a `fleet` record "
             "block (straggler_ratio, rank_step_skew, exchange_frac, "
             "scrape staleness — all alertable) and per-rank "
             "tffm_train_rank_* series on its /metrics (requires "
             "--heartbeat_secs; empty = off, bitwise-identical "
             "training)",
    )
    p.add_argument(
        "--no_quality", action="store_true",
        help="disable the model-quality & data-drift plane: no "
             "distribution sketches on the parse/serve paths, no "
             "windowed online eval or `quality` record block, no "
             "manifest sketch payload or serving skew detection "
             "(bitwise-identical training, byte-identical serving)",
    )
    p.add_argument(
        "--quality_window", type=int, default=None,
        help="examples per quality window: the drift sketches' "
             "rotation cadence (PSI compares adjacent windows) and "
             "the online-eval ring size",
    )
    p.add_argument(
        "--no_resource_metrics", action="store_true",
        help="disable the resource plane: no RSS/component-memory "
             "ledger, no compile sentinel (the train step dispatches "
             "through the plain jit path), no `resource` record block",
    )
    p.add_argument(
        "--no_blackbox", action="store_true",
        help="disable the incident flight recorder: no in-memory "
             "evidence rings, no incidents/ bundles on alert breach or "
             "crash, POST /incident answers 503 (bitwise-identical "
             "training, byte-identical serving)",
    )
    p.add_argument(
        "--incident_dir", default=None, metavar="DIR",
        help="where incident bundles land (default: "
             "<model_file>/incidents next to the checkpoint); each "
             "process suffixes its bundle dirs rankN/pidN/router so "
             "concurrent dumpers never collide",
    )
    p.add_argument(
        "--trace_rotate_events", type=int, default=None,
        help="rotate the trace buffer into trace.0.json, trace.1.json, "
             "... every N events (removes the in-memory cap for long "
             "traced runs; merge with tools/report.py --trace)",
    )
    # Online-serving knobs (serve mode; override the cfg file).
    p.add_argument(
        "--serve_port", type=int, default=None,
        help="serve mode: HTTP scoring endpoint port (POST /score + "
             "/metrics /status /healthz; 0 = OS-assigned, printed at "
             "startup)",
    )
    p.add_argument(
        "--serve_host", default=None, metavar="ADDR",
        help="bind address for --serve_port (default 127.0.0.1; the "
             "endpoint is unauthenticated, 0.0.0.0 is an explicit "
             "opt-in)",
    )
    p.add_argument(
        "--serve_batch_sizes", default=None, metavar="N,N,...",
        help="fixed microbatch shape ladder (example counts) requests "
             "pad/coalesce into; every rung precompiles at startup so "
             "steady-state serving never compiles",
    )
    p.add_argument(
        "--max_batch_wait_ms", type=float, default=None,
        help="request-coalescing deadline: dispatch a microbatch when "
             "the largest rung fills or this many ms pass (0 = "
             "dispatch immediately)",
    )
    p.add_argument(
        "--serve_poll_secs", type=float, default=None,
        help="poll the trainer-published checkpoint manifest every N "
             "seconds and hot-swap new params with zero recompiles "
             "(0 = serve the startup checkpoint forever)",
    )
    p.add_argument(
        "--replicas", type=int, default=None, dest="serve_replicas",
        help="serve mode: run N shared-nothing replica serve processes "
             "behind a power-of-two-choices router on --serve_port "
             "(0/1 = the classic single-process server)",
    )
    p.add_argument(
        "--serve_shed_deadline_ms", type=float, default=None,
        help="router admission budget: shed with a fast 429 when the "
             "projected queue delay exceeds this many ms (0 = admit "
             "everything)",
    )
    p.add_argument(
        "--serve_canary", action="store_true", default=None,
        help="canary checkpoint promotion: the router reloads ONE "
             "replica on a new manifest, shadow-compares its score "
             "distribution against a baseline replica (tools/report.py "
             "--compare), and only then promotes the fleet (requires "
             "--replicas >= 2)",
    )
    p.add_argument(
        "--no_serve_canary", action="store_true",
        help="force canary promotion OFF regardless of the cfg file "
             "(the fleet launcher passes this to every replica so an "
             "INI-configured canary fleet doesn't trip each child's "
             "serve_canary-requires-a-fleet validation)",
    )
    p.add_argument(
        "--serve_transport", choices=["text", "bin", "both"],
        default=None,
        help="request transports the scoring endpoints accept: libsvm "
             "text (POST /score), the binary frame (POST /score_bin), "
             "or both",
    )
    p.add_argument(
        "--serve_trace_sample", type=float, default=None,
        help="serve mode: trace this fraction of requests as a "
             "connected cross-process span chain (request id minted "
             "or from X-Request-Id, propagated router->replica and "
             "echoed back; requires --trace; 0 = off)",
    )
    p.add_argument(
        "--serve_capture_sample", type=float, default=None,
        help="serve mode: append this fraction of scored requests "
             "(request + response as canonical binary frames) to "
             "--serve_capture_file for post-hoc replay "
             "(tools/replay.py; 0 = off, serving is byte-identical)",
    )
    p.add_argument(
        "--serve_capture_file", default=None, metavar="PATH",
        help="TFC1 capture output path for --serve_capture_sample "
             "(rotates to PATH.1 at 64 MiB; a managed fleet gives "
             "each replica PATH.replicaN)",
    )
    p.add_argument(
        "--serve_slo_p99_ms", type=float, default=None,
        help="serving SLO latency objective: a completed request "
             "slower than this many ms counts against the error "
             "budget (0 = latency not in the SLO)",
    )
    p.add_argument(
        "--serve_slo_availability", type=float, default=None,
        help="serving SLO availability objective (e.g. 0.999): "
             "defines the error budget the rolling serve_burn_rate "
             "gauge divides by (0 = no burn-rate accounting)",
    )
    p.add_argument(
        "--serve_parse_mode", choices=["vec", "legacy"], default=None,
        help="POST /score text-parse engine: the vectorized batch "
             "parser (default) or the per-line legacy loop (both "
             "bitwise-identical; the knob exists for bisection)",
    )
    p.add_argument(
        "--serve_http_threads", type=int, default=None,
        help="HTTP front-end worker pool size for the scoring "
             "endpoints (0 = thread-per-connection legacy mode); "
             "size >= expected concurrent kept-alive connections",
    )
    p.add_argument(
        "--serve_http_acceptors", type=int, default=None,
        help="accept loops for the pooled front end (>1 uses "
             "SO_REUSEPORT listeners when the kernel supports it, "
             "shared-socket fallback otherwise)",
    )
    p.add_argument(
        "--interaction_impl", default=None,
        choices=["auto", "reference", "pallas", "packed"],
        help="device interaction path for the FM hot loop: 'auto' "
             "benchmarks the candidates for this run's shapes and "
             "promotes the fastest that matches reference (decision "
             "cached in autotune_cache.json); a named impl pins it "
             "with no measurement",
    )
    p.add_argument(
        "--compile_cache_dir", default=None, metavar="DIR",
        help="persistent XLA compilation cache directory: restarts "
             "and replica spawns replay their warmup compiles from "
             "disk instead of re-lowering (empty = off)",
    )
    p.add_argument(
        "--metrics_file", default=None, metavar="PATH",
        help="JSONL metrics stream path (overrides the cfg; a "
             "multi-replica fleet suffixes each replica's stream "
             ".replicaN)",
    )
    # Legacy reference flags (mapped, SURVEY.md §3.2).
    p.add_argument("--ps_hosts", default=None, help="legacy; ps tasks exit")
    p.add_argument("--worker_hosts", default=None,
                   help="legacy; maps to --num_processes")
    p.add_argument("--job_name", default=None, choices=[None, "ps", "worker"])
    p.add_argument("--task_index", type=int, default=None,
                   help="legacy; maps to --process_id")
    return p


def _resolve_dist(args) -> tuple[str, int, int] | None:
    """Map new+legacy flags to (coordinator, num_processes, process_id)."""
    if args.job_name == "ps":
        log.warning(
            "parameter-server tasks are obsolete: the table is row-sharded "
            "across compute chips (GSPMD). This ps task exits; remove ps "
            "entries from your launch scripts."
        )
        sys.exit(0)
    if args.coordinator is not None:
        if args.num_processes is None or args.process_id is None:
            raise SystemExit(
                "--coordinator requires --num_processes and --process_id"
            )
        return args.coordinator, args.num_processes, args.process_id
    if args.worker_hosts is not None:
        workers = [h for h in args.worker_hosts.split(",") if h]
        task = args.task_index or 0
        coordinator = workers[0]
        log.warning(
            "legacy --worker_hosts mapped to JAX multi-host: coordinator=%s "
            "num_processes=%d process_id=%d",
            coordinator, len(workers), task,
        )
        return coordinator, len(workers), task
    return None


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    from fast_tffm_tpu.config import load_config

    overrides = {
        key: getattr(args, key)
        for key in ("steps_per_dispatch", "prefetch_super_batches",
                    "parse_processes", "cache_epochs", "cache_max_bytes",
                    "cache_prestacked", "ring_slots", "heartbeat_secs",
                    "trace_file", "nan_policy", "table_tiering", "hot_rows",
                    "cold_dtype", "serve_table_dtype", "quant_chunk",
                    "status_port", "status_host", "alert_rules",
                    "train_fleet_scrape",
                    "trace_rotate_events", "serve_port", "serve_host",
                    "serve_batch_sizes", "max_batch_wait_ms",
                    "serve_poll_secs", "serve_replicas",
                    "serve_shed_deadline_ms", "serve_canary",
                    "serve_transport", "serve_trace_sample",
                    "serve_slo_p99_ms", "serve_slo_availability",
                    "serve_parse_mode", "serve_http_threads",
                    "serve_http_acceptors", "interaction_impl",
                    "compile_cache_dir", "incident_dir",
                    "serve_capture_sample", "serve_capture_file",
                    "quality_window", "metrics_file")
        if getattr(args, key) is not None
    }
    if args.no_telemetry:
        overrides["telemetry"] = False
    if args.no_resource_metrics:
        overrides["resource_metrics"] = False
    if args.no_quality:
        overrides["quality"] = False
    if args.no_serve_canary:
        overrides["serve_canary"] = False
    if args.no_blackbox:
        overrides["blackbox"] = False
    cfg = load_config(args.cfg, overrides or None)
    _setup_logging(cfg.log_file or None)
    dist = _resolve_dist(args)
    if dist is not None:
        from fast_tffm_tpu.train import dist as dist_lib

        dist_lib.initialize(*dist)

    if args.mode == "serve":
        if cfg.serve_replicas >= 2:
            from fast_tffm_tpu.serve.router import serve_fleet

            return serve_fleet(cfg, args.cfg, overrides)
        from fast_tffm_tpu.serve.server import serve_forever

        return serve_forever(cfg)

    from fast_tffm_tpu.train.loop import Trainer, predict

    if args.mode == "train":
        result = Trainer(cfg).train()
        m = result.get("validation", result["train"])
        log.info("done: %s", result)
        loss_name = "mse" if cfg.loss_type == "mse" else "logloss"
        print(
            f"train {loss_name}={result['train']['loss']:.6f} "
            f"auc={result['train']['auc']:.4f} "
            f"ex/s={result['train']['examples_per_sec']:.0f}"
        )
        if "validation" in result:
            print(
                f"validation {loss_name}={m['loss']:.6f} auc={m['auc']:.4f}"
            )
    else:
        n = predict(cfg)
        print(f"wrote {n} scores to {cfg.score_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
