"""Binary request transport: the wire codec both scoring endpoints and
the router share.

The hot path's text cost is the per-request libsvm parse (the
``serve.parse`` timer PR 11 added measured it); this frame format is
the zero-parse alternative: the handler's whole decode is a header
unpack + ``np.frombuffer`` views.  One frame per request, all fields
LITTLE-ENDIAN (documented in SERVING.md "Binary frame layout"):

    request:   magic  u8[4]  = b"TFB1"
               n      u32    examples in the frame (0 allowed)
               f      u32    features per example AS SENT
               flags  u8     bit 0 = a fields array follows
               ids    i32[n*f]   row-major [n, f]
               vals   f32[n*f]
               fields i32[n*f]   present iff flags bit 0

    response:  magic  u8[4]  = b"TFB1"
               n      u32
               scores f32[n]     same order as the request's examples

``f`` may differ from the server's ``max_features``: narrower frames
zero-pad (``vals == 0`` slots are mathematically inert), wider ones
truncate and count the dropped nonzero occurrences (the same
data-integrity semantics as the text path).  Ids reduce modulo
``vocabulary_size`` exactly like ``libsvm.parse_line``, so ``/score``
and ``/score_bin`` are bitwise-interchangeable for the same examples.

This module is deliberately jax-free (numpy + stdlib only): the router
process proxies frames and decodes shadow-score responses without ever
paying a jax import.
"""

from __future__ import annotations

import struct

import numpy as np

from fast_tffm_tpu.config import FmConfig

__all__ = [
    "BIN_MAGIC", "MAX_BODY_BYTES", "decode_bin_request",
    "decode_bin_response", "encode_bin_request", "encode_bin_response",
]

# POST body cap shared by every scoring endpoint (text and binary, the
# replicas and the router): far above any sane scoring request (a
# 64 MiB libsvm body is ~1M examples), far below what would hurt the
# host.
MAX_BODY_BYTES = 64 << 20

BIN_MAGIC = b"TFB1"
_BIN_HDR = struct.Struct("<4sIIB")
_BIN_RESP_HDR = struct.Struct("<4sI")


def encode_bin_request(ids, vals, fields=None) -> bytes:
    """``[n, f]`` arrays -> one request frame (the client half; tests,
    bench and the smoke build frames with it or from the documented
    layout directly)."""
    ids = np.ascontiguousarray(ids, np.int32)
    vals = np.ascontiguousarray(vals, np.float32)
    if ids.shape != vals.shape or ids.ndim != 2:
        raise ValueError(
            f"ids/vals must be matching [n, f] arrays, got "
            f"{ids.shape} vs {vals.shape}"
        )
    n, f = ids.shape
    parts = [
        _BIN_HDR.pack(BIN_MAGIC, n, f, 1 if fields is not None else 0),
        ids.tobytes(), vals.tobytes(),
    ]
    if fields is not None:
        fields = np.ascontiguousarray(fields, np.int32)
        if fields.shape != ids.shape:
            raise ValueError(
                f"fields shape {fields.shape} != ids shape {ids.shape}"
            )
        parts.append(fields.tobytes())
    return b"".join(parts)


def decode_bin_request(data: bytes, cfg: FmConfig):
    """One request frame -> ``(ids, vals, fields, n, truncated)`` with
    the arrays padded/truncated to ``[n, cfg.max_features]`` — the same
    contract as ``server.parse_request``, minus the text parse.  Raises
    ValueError (-> HTTP 400) on a malformed frame."""
    if len(data) < _BIN_HDR.size:
        raise ValueError(
            f"frame too short for the header ({len(data)} bytes)"
        )
    magic, n, f, flags = _BIN_HDR.unpack_from(data)
    if magic != BIN_MAGIC:
        raise ValueError(
            f"bad frame magic {magic!r} (want {BIN_MAGIC!r})"
        )
    has_fields = bool(flags & 1)
    if n and not f:
        # Zero features per example would make the length check
        # vacuous: an n-of-billions header over a 13-byte body must
        # not reach the [n, max_features] allocation below.
        raise ValueError(f"frame claims n={n} examples with f=0")
    cells = n * f
    want = _BIN_HDR.size + cells * (12 if has_fields else 8)
    if len(data) != want:
        raise ValueError(
            f"frame length {len(data)} != {want} expected for n={n} "
            f"f={f} fields={has_fields}"
        )
    off = _BIN_HDR.size
    ids = np.frombuffer(data, np.int32, cells, off).reshape(n, f)
    off += cells * 4
    vals = np.frombuffer(data, np.float32, cells, off).reshape(n, f)
    off += cells * 4
    fields = (
        np.frombuffer(data, np.int32, cells, off).reshape(n, f)
        if has_fields else None
    )
    F = cfg.max_features
    truncated = 0
    if f > F:
        # Same data-integrity semantics as the text path: a dropped
        # NONZERO occurrence means the example scores as a different
        # example; all-zero tails are plain padding.
        truncated = int(np.count_nonzero(vals[:, F:]))
        ids, vals = ids[:, :F], vals[:, :F]
        if fields is not None:
            fields = fields[:, :F]
    elif f < F:
        # Zero-pad by slice-assign into fresh buffers (np.pad's
        # generality costs real microseconds at request sizes, and
        # this path IS the latency path).
        pids = np.zeros((n, F), np.int32)
        pids[:, :f] = ids
        pvals = np.zeros((n, F), np.float32)
        pvals[:, :f] = vals
        ids, vals = pids, pvals
        if fields is not None:
            pf = np.zeros((n, F), np.int32)
            pf[:, :f] = fields
            fields = pf
    # The text path reduces every id modulo the vocabulary
    # (libsvm.parse_line); the binary path must agree or the two
    # transports would score out-of-range ids differently.  In-range
    # frames (every well-behaved client) pay two reductions and zero
    # copies.
    ids = _reduce_mod(ids, cfg.vocabulary_size)
    if fields is not None and cfg.field_num:
        fields = _reduce_mod(fields, cfg.field_num)
    return ids, vals, fields, int(n), truncated


def _reduce_mod(arr: np.ndarray, modulus: int) -> np.ndarray:
    """``arr % modulus`` with Python's nonnegative-remainder
    semantics, skipping the copy when every value is already in
    range."""
    if arr.size == 0 or (
        0 <= int(arr.min()) and int(arr.max()) < modulus
    ):
        return arr
    if modulus <= 0x7FFFFFFF:
        return np.mod(arr, np.int32(modulus))
    return (arr.astype(np.int64) % modulus).astype(np.int32)


def encode_bin_response(scores) -> bytes:
    scores = np.ascontiguousarray(scores, np.float32)
    return _BIN_RESP_HDR.pack(BIN_MAGIC, len(scores)) + scores.tobytes()


def decode_bin_response(data: bytes) -> np.ndarray:
    if len(data) < _BIN_RESP_HDR.size:
        raise ValueError(f"response frame too short ({len(data)} bytes)")
    magic, n = _BIN_RESP_HDR.unpack_from(data)
    if magic != BIN_MAGIC:
        raise ValueError(f"bad response magic {magic!r}")
    if len(data) != _BIN_RESP_HDR.size + 4 * n:
        raise ValueError(
            f"response frame length {len(data)} != header + {n} scores"
        )
    return np.frombuffer(data, np.float32, n, _BIN_RESP_HDR.size).copy()
