"""Binary request transport: the wire codec both scoring endpoints and
the router share.

The hot path's text cost is the per-request libsvm parse (the
``serve.parse`` timer PR 11 added measured it); this frame format is
the zero-parse alternative: the handler's whole decode is a header
unpack + ``np.frombuffer`` views.  One frame per request, all fields
LITTLE-ENDIAN (documented in SERVING.md "Binary frame layout"):

    request:   magic  u8[4]  = b"TFB1"
               n      u32    examples in the frame (0 allowed)
               f      u32    features per example AS SENT
               flags  u8     bit 0 = a fields array follows
                             bit 1 = a request-id trailer follows
               ids    i32[n*f]   row-major [n, f]
               vals   f32[n*f]
               fields i32[n*f]   present iff flags bit 0
               ridlen u16        present iff flags bit 1
               rid    u8[ridlen] utf-8 request id (distributed-trace
                                 correlation; <= 128 bytes)

    response:  magic  u8[4]  = b"TFB1"
               n      u32
               scores f32[n]     same order as the request's examples

The request-id trailer is the binary transport's spelling of the
``X-Request-Id`` header (SERVING.md "Request ids & distributed
tracing"): the router appends it to SAMPLED frames so the id rides the
frame itself across the proxy hop, and clients may set it directly.
It sits AFTER the arrays so the zero-copy ``np.frombuffer`` views are
untouched, and a frame without it is bit-for-bit the pre-trailer
layout — unsampled proxying stays byte-identical.

``f`` may differ from the server's ``max_features``: narrower frames
zero-pad (``vals == 0`` slots are mathematically inert), wider ones
truncate and count the dropped nonzero occurrences (the same
data-integrity semantics as the text path).  Ids reduce modulo
``vocabulary_size`` exactly like ``libsvm.parse_line``, so ``/score``
and ``/score_bin`` are bitwise-interchangeable for the same examples.

This module is deliberately jax-free (numpy + stdlib only): the router
process proxies frames and decodes shadow-score responses without ever
paying a jax import.
"""

from __future__ import annotations

import collections
import itertools
import logging
import os
import random
import struct
import threading
import time

import numpy as np

from fast_tffm_tpu.config import FmConfig

__all__ = [
    "BIN_MAGIC", "CAPTURE_MAGIC", "CaptureWriter", "MAX_BODY_BYTES",
    "MAX_REQUEST_ID_BYTES",
    "RequestSampler", "decode_bin_request", "decode_bin_response",
    "encode_bin_request", "encode_bin_response",
    "peek_bin_request_id", "read_capture", "valid_request_id",
    "with_bin_request_id",
]

log = logging.getLogger(__name__)

# POST body cap shared by every scoring endpoint (text and binary, the
# replicas and the router): far above any sane scoring request (a
# 64 MiB libsvm body is ~1M examples), far below what would hurt the
# host.
MAX_BODY_BYTES = 64 << 20

BIN_MAGIC = b"TFB1"
_BIN_HDR = struct.Struct("<4sIIB")
_BIN_RESP_HDR = struct.Struct("<4sI")
_RID_LEN = struct.Struct("<H")

# Frame flag bits.
_FLAG_FIELDS = 1
_FLAG_RID = 2

# Request-id cap (header values and frame trailers): ids are short
# correlation tokens, and an unauthenticated endpoint must not let a
# client inflate every span/log line with an arbitrary-length blob.
MAX_REQUEST_ID_BYTES = 128


def _rid_bytes(request_id: str) -> bytes:
    raw = request_id.encode("utf-8")
    if not raw or len(raw) > MAX_REQUEST_ID_BYTES:
        raise ValueError(
            f"request id must be 1..{MAX_REQUEST_ID_BYTES} utf-8 "
            f"bytes, got {len(raw)}"
        )
    return raw


def encode_bin_request(ids, vals, fields=None,
                       request_id=None) -> bytes:
    """``[n, f]`` arrays -> one request frame (the client half; tests,
    bench and the smoke build frames with it or from the documented
    layout directly).  ``request_id`` adds the flags-bit-1 trailer."""
    ids = np.ascontiguousarray(ids, np.int32)
    vals = np.ascontiguousarray(vals, np.float32)
    if ids.shape != vals.shape or ids.ndim != 2:
        raise ValueError(
            f"ids/vals must be matching [n, f] arrays, got "
            f"{ids.shape} vs {vals.shape}"
        )
    n, f = ids.shape
    flags = (_FLAG_FIELDS if fields is not None else 0) | (
        _FLAG_RID if request_id is not None else 0
    )
    parts = [
        _BIN_HDR.pack(BIN_MAGIC, n, f, flags),
        ids.tobytes(), vals.tobytes(),
    ]
    if fields is not None:
        fields = np.ascontiguousarray(fields, np.int32)
        if fields.shape != ids.shape:
            raise ValueError(
                f"fields shape {fields.shape} != ids shape {ids.shape}"
            )
        parts.append(fields.tobytes())
    if request_id is not None:
        raw = _rid_bytes(request_id)
        parts.append(_RID_LEN.pack(len(raw)) + raw)
    return b"".join(parts)


def _trailer_rid(data: bytes, arrays_end: int):
    """Decode the flags-bit-1 request-id trailer starting at
    ``arrays_end``; returns the id string.  Raises ValueError on a
    malformed trailer (wrong length accounting, empty/oversized id,
    non-utf-8 bytes)."""
    if len(data) < arrays_end + _RID_LEN.size:
        raise ValueError(
            "frame flags announce a request-id trailer but the body "
            "ends before its length field"
        )
    (ridlen,) = _RID_LEN.unpack_from(data, arrays_end)
    if not 0 < ridlen <= MAX_REQUEST_ID_BYTES:
        raise ValueError(
            f"request-id trailer length {ridlen} outside "
            f"(0, {MAX_REQUEST_ID_BYTES}]"
        )
    end = arrays_end + _RID_LEN.size + ridlen
    if len(data) != end:
        raise ValueError(
            f"frame length {len(data)} != {end} expected with a "
            f"{ridlen}-byte request-id trailer"
        )
    try:
        return data[arrays_end + _RID_LEN.size:end].decode("utf-8")
    except UnicodeDecodeError:
        raise ValueError(
            "request-id trailer is not valid utf-8"
        ) from None


def decode_bin_request(data: bytes, cfg: FmConfig):
    """One request frame -> ``(ids, vals, fields, n, truncated, rid)``
    with the arrays padded/truncated to ``[n, cfg.max_features]`` — the
    same contract as ``server.parse_request``, minus the text parse.
    ``rid`` is the request-id trailer (None without flags bit 1).
    Raises ValueError (-> HTTP 400) on a malformed frame."""
    if len(data) < _BIN_HDR.size:
        raise ValueError(
            f"frame too short for the header ({len(data)} bytes)"
        )
    magic, n, f, flags = _BIN_HDR.unpack_from(data)
    if magic != BIN_MAGIC:
        raise ValueError(
            f"bad frame magic {magic!r} (want {BIN_MAGIC!r})"
        )
    has_fields = bool(flags & _FLAG_FIELDS)
    if n and not f:
        # Zero features per example would make the length check
        # vacuous: an n-of-billions header over a 13-byte body must
        # not reach the [n, max_features] allocation below.
        raise ValueError(f"frame claims n={n} examples with f=0")
    cells = n * f
    want = _BIN_HDR.size + cells * (12 if has_fields else 8)
    rid = None
    if flags & _FLAG_RID:
        rid = _trailer_rid(data, want)
    elif len(data) != want:
        raise ValueError(
            f"frame length {len(data)} != {want} expected for n={n} "
            f"f={f} fields={has_fields}"
        )
    off = _BIN_HDR.size
    ids = np.frombuffer(data, np.int32, cells, off).reshape(n, f)
    off += cells * 4
    vals = np.frombuffer(data, np.float32, cells, off).reshape(n, f)
    off += cells * 4
    fields = (
        np.frombuffer(data, np.int32, cells, off).reshape(n, f)
        if has_fields else None
    )
    F = cfg.max_features
    truncated = 0
    if f > F:
        # Same data-integrity semantics as the text path: a dropped
        # NONZERO occurrence means the example scores as a different
        # example; all-zero tails are plain padding.
        truncated = int(np.count_nonzero(vals[:, F:]))
        ids, vals = ids[:, :F], vals[:, :F]
        if fields is not None:
            fields = fields[:, :F]
    elif f < F:
        # Zero-pad by slice-assign into fresh buffers (np.pad's
        # generality costs real microseconds at request sizes, and
        # this path IS the latency path).
        pids = np.zeros((n, F), np.int32)
        pids[:, :f] = ids
        pvals = np.zeros((n, F), np.float32)
        pvals[:, :f] = vals
        ids, vals = pids, pvals
        if fields is not None:
            pf = np.zeros((n, F), np.int32)
            pf[:, :f] = fields
            fields = pf
    # The text path reduces every id modulo the vocabulary
    # (libsvm.parse_line); the binary path must agree or the two
    # transports would score out-of-range ids differently.  In-range
    # frames (every well-behaved client) pay two reductions and zero
    # copies.
    ids = _reduce_mod(ids, cfg.vocabulary_size)
    if fields is not None and cfg.field_num:
        fields = _reduce_mod(fields, cfg.field_num)
    return ids, vals, fields, int(n), truncated, rid


def peek_bin_request_id(data: bytes):
    """The request-id trailer of a frame, WITHOUT decoding the arrays
    (the router's proxy path reads it in O(header)).  Returns None for
    frames without flags bit 1 or too short to carry a header; raises
    ValueError only for a frame that claims a trailer it doesn't
    carry (the replica's full decode rejects it the same way)."""
    if len(data) < _BIN_HDR.size:
        return None
    magic, n, f, flags = _BIN_HDR.unpack_from(data)
    if magic != BIN_MAGIC or not flags & _FLAG_RID:
        return None
    cells = n * f
    arrays_end = _BIN_HDR.size + cells * (
        12 if flags & _FLAG_FIELDS else 8
    )
    return _trailer_rid(data, arrays_end)


def with_bin_request_id(data: bytes, request_id: str) -> bytes:
    """A copy of ``data`` carrying ``request_id`` as its flags-bit-1
    trailer (the router stamps SAMPLED frames with this before
    proxying).  A frame that already carries a trailer keeps it — the
    client's id wins, same precedence as the X-Request-Id header."""
    if len(data) < _BIN_HDR.size:
        return data  # malformed; the replica's decode will 400 it
    magic, n, f, flags = _BIN_HDR.unpack_from(data)
    if magic != BIN_MAGIC or flags & _FLAG_RID:
        return data
    raw = _rid_bytes(request_id)
    return (
        _BIN_HDR.pack(BIN_MAGIC, n, f, flags | _FLAG_RID)
        + data[_BIN_HDR.size:]
        + _RID_LEN.pack(len(raw)) + raw
    )


def _reduce_mod(arr: np.ndarray, modulus: int) -> np.ndarray:
    """``arr % modulus`` with Python's nonnegative-remainder
    semantics, skipping the copy when every value is already in
    range."""
    if arr.size == 0 or (
        0 <= int(arr.min()) and int(arr.max()) < modulus
    ):
        return arr
    if modulus <= 0x7FFFFFFF:
        return np.mod(arr, np.int32(modulus))
    return (arr.astype(np.int64) % modulus).astype(np.int32)


def valid_request_id(rid) -> bool:
    """A usable client-supplied request id: non-empty, within the byte
    cap, printable ASCII only.  The id is echoed in a response HEADER:
    CR/LF would be response splitting, and anything http.server's
    latin-1-strict header encoder can't write would corrupt the
    kept-alive stream mid-response — so both the header path and the
    binary frame's trailer are screened through this before the id is
    ever reflected."""
    if not rid or len(rid) > MAX_REQUEST_ID_BYTES:
        return False
    return all(0x20 <= ord(ch) <= 0x7E for ch in rid)


class RequestSampler:
    """Request-id mint + the per-request trace-sampling decision.

    One instance per serving process (router or single server).  Ids
    are ``<tag>-<pid.in.hex>-<boot.ms>-<counter>`` — unique across the
    fleet's processes (pid + boot time) and under concurrency
    (``itertools.count``'s atomic ``next``).  ``sample()`` answers the
    ``serve_trace_sample`` coin flip; with sampling off it is a single
    attribute compare, and NO id is ever minted for an unsampled
    request (the no-allocation-on-the-unsampled-path contract the
    serving tests pin).
    """

    def __init__(self, sample: float, enabled: bool = True,
                 tag: str = "r"):
        self.rate = float(sample) if enabled else 0.0
        self._prefix = (
            f"{tag}-{os.getpid():x}-{int(time.time() * 1e3) & 0xFFFFFFFF:x}"
        )
        self._counter = itertools.count()
        # random.Random.random() is one C call — atomic under the GIL,
        # so concurrent handler threads need no lock around it.
        self._rng = random.Random(os.getpid() ^ 0x5EED)

    def sample(self) -> bool:
        if self.rate <= 0.0:
            return False
        return self.rate >= 1.0 or self._rng.random() < self.rate

    def mint(self) -> str:
        return f"{self._prefix}-{next(self._counter)}"


def encode_bin_response(scores) -> bytes:
    scores = np.ascontiguousarray(scores, np.float32)
    return _BIN_RESP_HDR.pack(BIN_MAGIC, len(scores)) + scores.tobytes()


def decode_bin_response(data: bytes) -> np.ndarray:
    if len(data) < _BIN_RESP_HDR.size:
        raise ValueError(f"response frame too short ({len(data)} bytes)")
    magic, n = _BIN_RESP_HDR.unpack_from(data)
    if magic != BIN_MAGIC:
        raise ValueError(f"bad response magic {magic!r}")
    if len(data) != _BIN_RESP_HDR.size + 4 * n:
        raise ValueError(
            f"response frame length {len(data)} != header + {n} scores"
        )
    return np.frombuffer(data, np.float32, n, _BIN_RESP_HDR.size).copy()


# ---------------------------------------------------------------------------
# Traffic capture (the TFC1 container): sampled live request/response
# pairs as raw TFB1 frames, replayable bit-for-bit by tools/replay.py.
#
#     file:    magic   u8[4] = b"TFC1"
#              version u32   = 1
#     record:  time     f64   unix seconds at capture
#              req_len  u32   bytes of the TFB1 REQUEST frame following
#              resp_len u32   bytes of the TFB1 RESPONSE frame following
#              req      u8[req_len]
#              resp     u8[resp_len]
#
# Requests are captured in CANONICAL form — the decoded (padded to
# max_features, id-reduced) arrays re-encoded as a binary frame — so a
# text /score request and a narrow binary frame both replay through
# /score_bin, and re-decoding a captured frame is idempotent: replay
# scores are bitwise-equal to the captured response (pinned by test).

CAPTURE_MAGIC = b"TFC1"
CAPTURE_VERSION = 1
_CAP_HDR = struct.Struct("<4sI")
_CAP_REC = struct.Struct("<dII")


class CaptureWriter:
    """Rotating sampled request/response capture (``serve_capture_*``).

    One instance per serving replica.  ``sample()`` answers the
    per-request coin flip (same no-work-when-unsampled contract as
    :class:`RequestSampler`); ``write(req, resp)`` appends one record
    under a lock and keeps the last ``tail`` records in memory for the
    blackbox's ``requests.capture`` bundle artifact
    (:meth:`tail_bytes`).  When the file passes ``rotate_bytes`` it
    rotates to ``<path>.1`` (one generation kept) so an unattended
    capture is disk-bounded.  Write failures are counted and logged,
    never raised — capture is forensics, not the request path.
    """

    def __init__(self, path: str, sample: float = 1.0,
                 rotate_bytes: int = 64 << 20, tail: int = 32,
                 telemetry=None, clock=time.time):
        self.path = path
        self.rate = float(sample)
        self._rotate_bytes = int(rotate_bytes)
        self._tail = collections.deque(maxlen=max(1, tail))
        self._clock = clock
        self._lock = threading.Lock()
        self._rng = random.Random(os.getpid() ^ 0xCA9)
        self.count = 0
        self.errors = 0
        self._closed = False
        self._c_captured = (
            telemetry.counter("serve.capture_requests")
            if telemetry is not None else None
        )
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._f = open(path, "wb")
        self._f.write(_CAP_HDR.pack(CAPTURE_MAGIC, CAPTURE_VERSION))
        self._size = _CAP_HDR.size

    def sample(self) -> bool:
        if self.rate <= 0.0 or self._closed:
            return False
        return self.rate >= 1.0 or self._rng.random() < self.rate

    def write(self, req: bytes, resp: bytes) -> None:
        t = self._clock()
        rec = _CAP_REC.pack(t, len(req), len(resp))
        with self._lock:
            if self._closed:
                return
            try:
                self._f.write(rec)
                self._f.write(req)
                self._f.write(resp)
                self._f.flush()
            except OSError as e:
                self.errors += 1
                log.warning("capture write failed: %s", e)
                return
            self._size += len(rec) + len(req) + len(resp)
            self.count += 1
            self._tail.append((t, req, resp))
            if self._size >= self._rotate_bytes:
                self._rotate_locked()
        if self._c_captured is not None:
            self._c_captured.add()

    def _rotate_locked(self) -> None:
        try:
            self._f.close()
            os.replace(self.path, self.path + ".1")
            self._f = open(self.path, "wb")
            self._f.write(_CAP_HDR.pack(CAPTURE_MAGIC, CAPTURE_VERSION))
            self._size = _CAP_HDR.size
        except OSError as e:
            self.errors += 1
            log.warning("capture rotation failed: %s", e)

    def tail_bytes(self) -> bytes:
        """The in-memory tail rendered as a standalone TFC1 file — the
        blackbox bundle's ``requests.capture`` artifact."""
        with self._lock:
            records = list(self._tail)
        parts = [_CAP_HDR.pack(CAPTURE_MAGIC, CAPTURE_VERSION)]
        for t, req, resp in records:
            parts.append(_CAP_REC.pack(t, len(req), len(resp)))
            parts.append(req)
            parts.append(resp)
        return b"".join(parts)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                self._f.close()
            except OSError:
                pass


def read_capture(path: str):
    """Iterate ``(time, request_frame, response_frame)`` records of a
    TFC1 capture file.  Raises ValueError on a bad header; a TRUNCATED
    final record (the writer died mid-append) is dropped silently —
    everything before it is intact by construction."""
    with open(path, "rb") as f:
        hdr = f.read(_CAP_HDR.size)
        if len(hdr) < _CAP_HDR.size:
            raise ValueError(f"{path}: too short for a capture header")
        magic, version = _CAP_HDR.unpack(hdr)
        if magic != CAPTURE_MAGIC:
            raise ValueError(
                f"{path}: bad capture magic {magic!r} "
                f"(want {CAPTURE_MAGIC!r})"
            )
        if version != CAPTURE_VERSION:
            raise ValueError(
                f"{path}: capture version {version} unsupported "
                f"(want {CAPTURE_VERSION})"
            )
        while True:
            rec = f.read(_CAP_REC.size)
            if len(rec) < _CAP_REC.size:
                return
            t, req_len, resp_len = _CAP_REC.unpack(rec)
            req = f.read(req_len)
            resp = f.read(resp_len)
            if len(req) < req_len or len(resp) < resp_len:
                return
            yield t, req, resp
