"""Online serving path: compiled fixed-shape scoring + request batching.

``serve.scorer`` owns the compiled scoring functions — a small ladder of
fixed microbatch shapes, precompiled through an AOT ``.lower().compile()``
cache with donated input buffers, so steady-state serving never compiles
(``FixedShapeScorer`` for dense checkpoints, ``OverlayScorer`` for
huge-V ``tiered.npz`` sparse overlays).  ``serve.batcher`` coalesces
concurrent requests into microbatches under a ``max_batch_wait_ms``
deadline.  ``serve.server`` mounts the whole thing behind a stdlib HTTP
endpoint (``POST /score`` + the same ``/metrics``/``/status`` surface as
``obs/status.py``) with warm checkpoint hot-swap driven by the trainer's
save-path manifest.  See SERVING.md for the dataflow.
"""

from fast_tffm_tpu.serve.batcher import ServeBatcher
from fast_tffm_tpu.serve.scorer import (
    FixedShapeScorer, OverlayScorer, load_model, make_scorer,
)
from fast_tffm_tpu.serve.server import ServeHandle, serve, serve_forever

__all__ = [
    "FixedShapeScorer", "OverlayScorer", "ServeBatcher", "ServeHandle",
    "load_model", "make_scorer", "serve", "serve_forever",
]
