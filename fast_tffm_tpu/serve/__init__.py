"""Online serving path: compiled fixed-shape scoring + request batching.

``serve.scorer`` owns the compiled scoring functions — a small ladder of
fixed microbatch shapes, precompiled through an AOT ``.lower().compile()``
cache with donated input buffers, so steady-state serving never compiles
(``FixedShapeScorer`` for dense checkpoints, ``OverlayScorer`` for
huge-V ``tiered.npz`` sparse overlays).  ``serve.batcher`` coalesces
concurrent requests into microbatches under a ``max_batch_wait_ms``
deadline.  ``serve.server`` mounts the whole thing behind a stdlib HTTP
endpoint (``POST /score`` + the same ``/metrics``/``/status`` surface as
``obs/status.py``) with warm checkpoint hot-swap driven by the trainer's
save-path manifest.  See SERVING.md for the dataflow.
"""

# Lazy re-exports (PEP 562): the convenience names below pull in jax
# (scorer -> jax, server -> scorer), but this package also hosts the
# ROUTER process's jax-free modules (serve.wire, serve.router) — an
# eager import here would defeat that, so the heavy modules load only
# when one of their names is actually touched.
_EXPORTS = {
    "ServeBatcher": "fast_tffm_tpu.serve.batcher",
    "SloTracker": "fast_tffm_tpu.serve.slo",
    "FixedShapeScorer": "fast_tffm_tpu.serve.scorer",
    "OverlayScorer": "fast_tffm_tpu.serve.scorer",
    "load_model": "fast_tffm_tpu.serve.scorer",
    "make_scorer": "fast_tffm_tpu.serve.scorer",
    "ServeHandle": "fast_tffm_tpu.serve.server",
    "serve": "fast_tffm_tpu.serve.server",
    "serve_forever": "fast_tffm_tpu.serve.server",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    if name in _EXPORTS:
        import importlib

        return getattr(importlib.import_module(_EXPORTS[name]), name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )
