"""Vectorized libsvm/ffm request parsing for the serving hot path.

The reference system exists because per-example Python parsing was too
slow — its ``FmParser`` is a native *batch* parser precisely so the
per-example cost is amortized (PAPER.md §1).  The serving endpoint
re-introduced that class of cost: ``parse_request`` walked the request
body one line at a time through :func:`libsvm.parse_line`, paying a
``tok.split(":")`` + two regex fullmatches + three list appends per
feature token (``serve.parse`` p50 ≈ 2.7x the binary transport's
``serve.parse_bin`` decode on the bench bodies).

This module is the batch rewrite, in the style of ``serve/wire.py``'s
binary decode: tokenize the WHOLE body once, validate every token with
ONE compiled-regex scan, recover the token structure with an
``np.frombuffer`` byte scan (space/colon masks -> token ids -> per-token
colon counts), convert ids/values/fields with ``np.fromiter`` over the
builtin ``int``/``float`` (bit-identical to the per-token conversions),
and scatter into the padded ``(ids, vals, fields)`` arrays with one
fancy-indexed assignment.

The contract is BITWISE equality with the legacy parser — including the
first-token-is-label rule, comment/blank skipping, ``max_features``
truncation counting, and per-line ``ValueError`` attribution.  The fast
path is *optimistic*: its validation grammar is exactly the accepted
language (with narrow digit-count caps for int64 safety), and on ANY
anomaly — a malformed token, an oversized integer literal, a vocabulary
that cannot index an int32 table — it falls back to re-parsing the whole
body through the legacy path, which reproduces the legacy behavior (and
the legacy error text, naming the offending line) by construction.
Errors are not the hot path; correctness there is worth a reparse.

:class:`ParseScratchPool` is the allocation-discipline half: recycled
per-request ``(ids, vals, fields)`` scratch bucketed by power-of-two row
capacity, the scorer's per-rung staging-buffer idiom applied to request
parsing, so steady-state text scoring allocates near-zero per request.
Lifecycle: the HTTP handler acquires through ``parse_request(...,
pool=...)`` and hands an ``on_done`` release callback to the batcher;
the dispatcher fires it exactly once after the microbatch copy and the
quality fold — the last readers of the request arrays.

jax-free on purpose (numpy + the hash oracle only), like ``wire.py``.
"""

from __future__ import annotations

import logging
import re
import threading
from typing import Optional

import numpy as np

from fast_tffm_tpu.data import libsvm

log = logging.getLogger(__name__)

__all__ = ["ParseScratchPool", "parse_request"]


class _Fallback(Exception):
    """Internal: the fast path declined; re-parse through legacy."""


# The accepted token language, mirroring libsvm.py's strict ASCII
# grammar (_FLOAT_RE / _INT_RE) exactly — anything outside it must fall
# back so the LEGACY path raises the legacy error text.  The only
# narrowing: integer literals are capped at 18 digits (ids) / 9 digits
# (fields) so the vectorized int64/int32 conversions cannot overflow;
# longer literals are valid legacy input and simply take the fallback.
_FLOAT = (
    r"(?:[+-]?(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?"
    r"|[+-]?(?:inf(?:inity)?|nan))"
)
_FIELD = r"[+-]?\d{1,9}"
_INT_ID = r"[+-]?\d{1,18}"
_HASH_ID = r"[^\s:]*"

_LABELS_RE = re.compile(
    f"{_FLOAT}(?: {_FLOAT})*", re.IGNORECASE | re.ASCII
)


def _feats_re(hash_mode: bool) -> re.Pattern:
    # Alternatives per token: field:id:val | id:val | bare id.  Ordered
    # 2-piece first (the dominant production traffic shape).  Tokens
    # contain no whitespace (they come from str.split), so the joined
    # validation string maps one token to exactly one alternative.
    if hash_mode:
        one = (
            f"(?:{_HASH_ID}:{_FLOAT}|{_FIELD}:{_HASH_ID}:{_FLOAT}"
            f"|[^\\s:]+)"
        )
    else:
        one = (
            f"(?:{_INT_ID}:{_FLOAT}|{_FIELD}:{_INT_ID}:{_FLOAT}"
            f"|{_INT_ID})"
        )
    return re.compile(f"{one}(?: {one})*", re.IGNORECASE | re.ASCII)


_FEATS_RE = _feats_re(False)
_FEATS_HASH_RE = _feats_re(True)

# Uniform fast lanes: production scoring traffic is overwhelmingly
# homogeneous `id:val` (or ffm `field:id:val`) tokens, and a body that
# matches one of these shapes end-to-end needs NO per-token structure
# recovery — the flat piece list alternates with a fixed stride, so the
# byte scan, bincount, and object-array gathers all collapse into list
# slicing.  On 1-line bodies this is the difference between beating the
# per-line parser and losing to numpy call overhead.
_UNI2_RE = re.compile(
    f"{_INT_ID}:{_FLOAT}(?: {_INT_ID}:{_FLOAT})*",
    re.IGNORECASE | re.ASCII,
)
_UNI2_HASH_RE = re.compile(
    f"{_HASH_ID}:{_FLOAT}(?: {_HASH_ID}:{_FLOAT})*",
    re.IGNORECASE | re.ASCII,
)
_UNI3_RE = re.compile(
    f"{_FIELD}:{_INT_ID}:{_FLOAT}(?: {_FIELD}:{_INT_ID}:{_FLOAT})*",
    re.IGNORECASE | re.ASCII,
)
_UNI3_HASH_RE = re.compile(
    f"{_FIELD}:{_HASH_ID}:{_FLOAT}(?: {_FIELD}:{_HASH_ID}:{_FLOAT})*",
    re.IGNORECASE | re.ASCII,
)

_INT32_MAX = np.iinfo(np.int32).max


class ParseScratchPool:
    """Recycled per-request parse scratch (ids/vals/fields triples).

    Buffers are bucketed by power-of-two row capacity and zero-filled
    on acquire (padding slots must stay inert).  Requests above
    ``max_pooled_rows`` get fresh untracked arrays — a single giant
    request must not pin its high-water footprint forever.  ``release``
    takes any of the returned row views and recovers the backing
    buffer; releasing an untracked (or already-released) array is a
    no-op, so the release callback is safe to fire from any failure
    path.  Thread-safe: handlers on different pool workers acquire
    concurrently.

    Telemetry (optional): ``serve.parse_scratch_reuse`` counts recycled
    acquires (the steady state should be all-reuse, the analogue of
    ``prefetch.staging_reuse``), ``serve.parse_scratch_bytes`` gauges
    the pool-owned buffer bytes (free + leased).
    """

    def __init__(self, max_features: int, telemetry=None,
                 max_pooled_rows: int = 4096,
                 max_free_per_bucket: int = 32):
        self._F = max(1, int(max_features))
        self._max_rows = int(max_pooled_rows)
        self._max_free = int(max_free_per_bucket)
        self._free: dict = {}    # cap -> [bufs, ...]
        self._leased: dict = {}  # id(ids buffer) -> (cap, bufs)
        self._bytes = 0
        self._lock = threading.Lock()
        self._c_reuse = (
            telemetry.counter("serve.parse_scratch_reuse")
            if telemetry is not None else None
        )
        self._g_bytes = (
            telemetry.gauge("serve.parse_scratch_bytes")
            if telemetry is not None else None
        )

    def _alloc(self, rows: int):
        return (
            np.zeros((rows, self._F), np.int32),
            np.zeros((rows, self._F), np.float32),
            np.zeros((rows, self._F), np.int32),
        )

    def acquire(self, n: int):
        """Zero-filled ``(ids, vals, fields)`` views of shape
        ``(n, max_features)`` backed by recycled buffers."""
        if n > self._max_rows:
            return self._alloc(n)
        cap = 1
        while cap < n:
            cap <<= 1
        with self._lock:
            stack = self._free.get(cap)
            bufs = stack.pop() if stack else None
        if bufs is None:
            bufs = self._alloc(cap)
            with self._lock:
                self._bytes += sum(b.nbytes for b in bufs)
                if self._g_bytes is not None:
                    self._g_bytes.set(self._bytes)
        else:
            if self._c_reuse is not None:
                self._c_reuse.add()
            for b in bufs:
                b[:n].fill(0)
        with self._lock:
            self._leased[id(bufs[0])] = (cap, bufs)
        return bufs[0][:n], bufs[1][:n], bufs[2][:n]

    def release(self, ids_view) -> None:
        """Return a leased buffer (identified by any row view of its
        ids array) to the free list.  No-op for untracked arrays."""
        base = ids_view.base if ids_view.base is not None else ids_view
        with self._lock:
            entry = self._leased.pop(id(base), None)
            if entry is None:
                return
            cap, bufs = entry
            stack = self._free.setdefault(cap, [])
            if len(stack) < self._max_free:
                stack.append(bufs)
            else:
                self._bytes -= sum(b.nbytes for b in bufs)
                if self._g_bytes is not None:
                    self._g_bytes.set(self._bytes)

    @property
    def leased(self) -> int:
        with self._lock:
            return len(self._leased)


def _acquire(pool: Optional[ParseScratchPool], n: int, F: int):
    if pool is not None:
        return pool.acquire(n)
    return (
        np.zeros((n, F), np.int32),
        np.zeros((n, F), np.float32),
        np.zeros((n, F), np.int32),
    )


def _parse_legacy(text: str, cfg, pool: Optional[ParseScratchPool]):
    """The per-line oracle path: one :func:`libsvm.parse_line` per
    line, filling the padded arrays DIRECTLY (one sliced assignment per
    row — the old intermediate ``examples`` list and its second
    row-by-row copy are gone).  Also the fast path's fallback, so its
    behavior — including error text — IS the parse contract."""
    F = cfg.max_features
    rows = []
    for lineno, line in enumerate(text.splitlines(), 1):
        stripped = line.strip()
        if not stripped or stripped.startswith("#"):
            continue
        rows.append((lineno, stripped))
    n = len(rows)
    ids, vals, fields = _acquire(pool, n, F)
    truncated = 0
    try:
        for i, (lineno, stripped) in enumerate(rows):
            if ":" in stripped.split(None, 1)[0]:
                # First token carries ':' -> label-less client line;
                # graft the ignored label column parse_line expects.
                stripped = "0 " + stripped
            try:
                ex = libsvm.parse_line(
                    stripped, cfg.vocabulary_size, cfg.hash_feature_id,
                    cfg.field_num,
                )
            except ValueError as e:
                raise ValueError(f"line {lineno}: {e}") from e
            k = min(len(ex.ids), F)
            truncated += len(ex.ids) - k
            ids[i, :k] = ex.ids[:k]
            vals[i, :k] = ex.vals[:k]
            fields[i, :k] = ex.fields[:k]
    except BaseException:
        if pool is not None:
            pool.release(ids)
        raise
    return ids, vals, fields, n, truncated


def _conv_ids(id_strs, hash_mode: bool, vocab: int, count: int):
    """Feature-id strings -> int64 bucket array, bit-identical to the
    per-token legacy conversion (numpy ``%`` with a positive divisor
    matches Python's sign convention)."""
    if hash_mode:
        hb = libsvm.hash_bucket
        return np.fromiter(
            (hb(s, vocab) for s in id_strs), np.int64, count=count
        )
    return np.fromiter(map(int, id_strs), np.int64, count=count) % vocab


def _parse_vec(text: str, cfg, pool: Optional[ParseScratchPool]):
    """The optimistic batch path.  Raises :class:`_Fallback` (never a
    user-facing error) whenever the body strays from the fast grammar;
    acquires scratch only after the last fallible step, so a fallback
    leaks nothing."""
    F = cfg.max_features
    vocab = cfg.vocabulary_size
    if vocab > _INT32_MAX:
        raise _Fallback  # legacy owns the (crashing) overflow behavior
    hash_mode = cfg.hash_feature_id
    labels: list = []
    feats: list = []
    nfeat: list = []
    for line in text.splitlines():
        s = line.strip()
        if not s or s.startswith("#"):
            continue
        toks = s.split()
        if ":" in toks[0]:
            nfeat.append(len(toks))
            feats.extend(toks)
        else:
            labels.append(toks[0])
            nfeat.append(len(toks) - 1)
            feats.extend(toks[1:])
    n = len(nfeat)
    if labels and _LABELS_RE.fullmatch(" ".join(labels)) is None:
        raise _Fallback
    ntok = len(feats)
    if ntok == 0:
        ids, vals, fields = _acquire(pool, n, F)
        return ids, vals, fields, n, 0
    joined = " ".join(feats)
    # Uniform lanes first: the total colon count is a one-pass
    # discriminator (ntok colons <-> possibly all id:val, 2*ntok <->
    # possibly all field:id:val), confirmed by the matching uniform
    # regex.  A confirmed uniform body needs no structure recovery at
    # all — the flat piece list strides by 2 (or 3).  ``fields_t is
    # None`` means "all fields zero": the scratch is already
    # zero-filled, and 0 stays 0 under any field_num fold.
    ncolon = joined.count(":")
    fids = vals_t = fields_t = None
    try:
        if ncolon == ntok and (
            _UNI2_HASH_RE if hash_mode else _UNI2_RE
        ).fullmatch(joined) is not None:
            parts = joined.replace(":", " ").split(" ")
            fids = _conv_ids(parts[0::2], hash_mode, vocab, ntok)
            vals_t = np.fromiter(
                map(float, parts[1::2]), np.float64, count=ntok
            )
        elif ncolon == 2 * ntok and (
            _UNI3_HASH_RE if hash_mode else _UNI3_RE
        ).fullmatch(joined) is not None:
            parts = joined.replace(":", " ").split(" ")
            fids = _conv_ids(parts[1::3], hash_mode, vocab, ntok)
            vals_t = np.fromiter(
                map(float, parts[2::3]), np.float64, count=ntok
            )
            fields_t = np.fromiter(
                map(int, parts[0::3]), np.int64, count=ntok
            )
            if cfg.field_num:
                fields_t %= cfg.field_num
    except (ValueError, OverflowError):
        raise _Fallback from None
    if fids is None:
        fids, vals_t, fields_t = _parse_mixed(joined, ntok, cfg)
    # Scatter into the padded rows; slots beyond max_features are the
    # truncation the legacy loop counts with len(ex.ids) - k.
    if n == 1:
        k = ntok if ntok <= F else F
        ids, vals, fields = _acquire(pool, 1, F)
        ids[0, :k] = fids[:k]
        vals[0, :k] = vals_t[:k]
        if fields_t is not None:
            fields[0, :k] = fields_t[:k]
        return ids, vals, fields, 1, ntok - k
    L = nfeat[0]
    if ntok == n * L and nfeat.count(L) == n:
        # Equal-length lines (the common batch shape): one reshaped
        # block assignment per array instead of a fancy-index scatter.
        k = L if L <= F else F
        ids, vals, fields = _acquire(pool, n, F)
        ids[:, :k] = fids.reshape(n, L)[:, :k]
        vals[:, :k] = vals_t.reshape(n, L)[:, :k]
        if fields_t is not None:
            fields[:, :k] = fields_t.reshape(n, L)[:, :k]
        return ids, vals, fields, n, (L - k) * n
    nfeat_a = np.asarray(nfeat, np.int64)
    cum0 = np.zeros(n, np.int64)
    np.cumsum(nfeat_a[:-1], out=cum0[1:])
    line_of = np.repeat(np.arange(n), nfeat_a)
    slot = np.arange(ntok, dtype=np.int64) - cum0[line_of]
    keep = slot < F
    truncated = ntok - int(keep.sum())
    if truncated:
        line_of = line_of[keep]
        slot = slot[keep]
        fids = fids[keep]
        vals_t = vals_t[keep]
        if fields_t is not None:
            fields_t = fields_t[keep]
    ids, vals, fields = _acquire(pool, n, F)
    ids[line_of, slot] = fids
    vals[line_of, slot] = vals_t
    if fields_t is not None:
        fields[line_of, slot] = fields_t
    return ids, vals, fields, n, truncated


def _parse_mixed(joined: str, ntok: int, cfg):
    """Mixed-shape lane: full alternation validation, then structure
    recovery wire.py-style with one byte scan of the joined tokens.
    ' ' (0x20) and ':' (0x3a) bytes never occur inside UTF-8 multibyte
    sequences, so byte masks are exact even for hashed unicode ids.
    Returns flat ``(fids, vals_t, fields_t_or_None)`` token arrays."""
    vocab = cfg.vocabulary_size
    pat = _FEATS_HASH_RE if cfg.hash_feature_id else _FEATS_RE
    if pat.fullmatch(joined) is None:
        raise _Fallback
    buf = np.frombuffer(joined.encode("utf-8"), np.uint8)
    tok_of = np.cumsum(buf == 0x20)
    ncol = np.bincount(tok_of[buf == 0x3A], minlength=ntok)
    pieces = np.array(
        joined.replace(":", " ").split(" "), dtype=object
    )
    if len(pieces) != ntok + int(ncol.sum()):
        raise _Fallback  # cannot happen post-validation; stay safe
    starts = np.zeros(ntok, np.int64)
    np.cumsum(ncol[:-1] + 1, out=starts[1:])
    three = ncol == 2
    try:
        # ids: 2nd piece of field:id:val tokens, 1st piece otherwise.
        fids = _conv_ids(
            pieces[starts + three], cfg.hash_feature_id, vocab, ntok
        )
        # values: last piece when any colon, else the implicit 1.0 of
        # a bare feature id.  map(float) keeps the double->float32
        # rounding bit-identical to the per-token legacy conversion.
        vals_t = np.ones(ntok, np.float64)
        has_val = ncol >= 1
        nv = int(has_val.sum())
        if nv == ntok:
            vals_t = np.fromiter(
                map(float, pieces[starts + ncol]), np.float64,
                count=ntok,
            )
        elif nv:
            vals_t[has_val] = np.fromiter(
                map(float, pieces[(starts + ncol)[has_val]]),
                np.float64, count=nv,
            )
        fields_t = None
        n3 = int(three.sum())
        if n3:
            fields_t = np.zeros(ntok, np.int64)
            fields_t[three] = np.fromiter(
                map(int, pieces[starts[three]]), np.int64, count=n3
            )
            if cfg.field_num:
                fields_t %= cfg.field_num
    except (ValueError, OverflowError):
        raise _Fallback from None
    return fids, vals_t, fields_t


def parse_request(text: str, cfg,
                  pool: Optional[ParseScratchPool] = None):
    """Request body -> ``(ids, vals, fields, n, truncated)`` arrays.

    One example per non-blank, non-comment line, ``predict_files``
    format.  A line whose FIRST token contains ``:`` is treated as
    label-less (scoring clients rarely have labels); anything else
    reads its first token as the label, so request files and predict
    files are interchangeable.  NOTE the inherent libsvm ambiguity this
    rule resolves deterministically: a line of BARE feature ids
    ("123 456 789") is indistinguishable from a labeled line, so its
    first token is always read as the label — bare-id clients must
    send an explicit label column (or ``id:1`` tokens); documented in
    SERVING.md.  Raises ValueError (-> HTTP 400) on a malformed line,
    naming the line.  ``truncated`` counts feature occurrences dropped
    by ``max_features`` — a truncated example scores as a DIFFERENT
    example, the same data-integrity event the ingest path surfaces as
    ``ingest.truncated_features`` (the server counts it as
    ``serve.truncated_features``).

    ``cfg.serve_parse_mode`` picks the engine: ``"vec"`` (default) runs
    the vectorized batch parser with automatic legacy fallback on any
    out-of-grammar input; ``"legacy"`` forces the per-line oracle.
    Both produce bitwise-identical arrays and errors (pinned by test).
    ``pool`` (optional) recycles the returned arrays' backing buffers;
    the caller owns the lease and releases via ``pool.release(ids)``
    once the batcher is done reading them.
    """
    if getattr(cfg, "serve_parse_mode", "vec") != "legacy":
        try:
            return _parse_vec(text, cfg, pool)
        except _Fallback:
            pass
    return _parse_legacy(text, cfg, pool)
