"""Request-batching front end: coalesce concurrent requests into
fixed-shape microbatches.

One scoring dispatch amortizes over every example in it, so serving
throughput lives or dies on batch fill — but a request must not wait
forever for company.  :class:`ServeBatcher` is the standard tradeoff
dial: concurrent requests land in a bounded queue (the ingest layer's
``_ClosableQueue``, depth-histogrammed as ``serve.queue_depth``), and a
single dispatcher thread coalesces them into one microbatch until
either the largest ladder rung fills or ``max_batch_wait_ms`` expires
— whichever comes first.  An idle server costs a lone request at most
the deadline; a saturated server fills rungs and the deadline never
fires.

The dispatcher fills its own recycled per-rung staging buffers
directly (one row copy per example, no per-request concatenation —
the prefetcher's staging-pool discipline applied to requests), runs
ONE scorer dispatch, then splits the scores back per request and
releases the waiting client threads.  Because dispatches are serial
and the scorer resolves its model reference once per dispatch, a hot
swap can never interleave old and new params inside one microbatch.

Instruments (all ``serve.*``, documented in OBSERVABILITY.md):
``requests`` / ``examples`` / ``batches`` counters, the ``latency``
timer (enqueue -> scores delivered; p50/p95/p99 ride every snapshot),
the ``batch_fill`` gauge (cumulative filled/dispatched slots), and the
``queue_depth`` histogram.

Distributed tracing: a request carrying a request id (``rid``, from
the ``X-Request-Id`` header or the binary frame's trailer on a SAMPLED
request) gets per-request spans — ``serve.queue_wait`` (enqueue ->
picked by the dispatcher), ``serve.coalesce`` (picked -> the
microbatch dispatches) and ``serve.dispatch`` (the rung dispatch, with
a flow step on the rid) — emitted from recorded timestamps AFTER the
dispatch, so the hot path pays nothing but two ``perf_counter`` reads.
A rid-less request touches none of it (the unsampled path is the
pre-trace code path).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

import numpy as np

from fast_tffm_tpu import obs
from fast_tffm_tpu.data.pipeline import (
    _CANCELLED, _TIMEOUT, _ClosableQueue,
)
from fast_tffm_tpu.obs.trace import NULL_TRACER

log = logging.getLogger(__name__)

__all__ = ["ScoreRequest", "ServeBatcher"]


class ScoreRequest:
    """One in-flight scoring request (a future the client waits on).

    ``rid`` is the distributed-trace request id (None = unsampled);
    ``t_picked`` is stamped by the dispatcher when the request leaves
    the queue, only for rid-carrying requests (span reconstruction
    needs it; the unsampled path skips the write).

    ``on_done`` is the scratch-release hook for pooled parse buffers
    (serve/textparse.py): the batcher fires it exactly once when it is
    DONE READING ``ids``/``vals``/``fields`` — after the microbatch
    copy and the quality fold on the success path, after stamping the
    error on every failure path.  The client's ``result()`` wait is
    NOT the release point: a client timeout abandons a request the
    dispatcher still holds, and releasing then would let the pool hand
    the buffer to a new request while the dispatcher reads it."""

    __slots__ = ("ids", "vals", "fields", "n", "event", "scores",
                 "error", "t0", "rid", "t_picked", "on_done")

    def __init__(self, ids, vals, fields, rid=None, on_done=None):
        self.ids = ids
        self.vals = vals
        self.fields = fields
        self.n = len(ids)
        self.event = threading.Event()
        self.scores: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.t0 = time.perf_counter()
        self.rid = rid
        self.t_picked: Optional[float] = None
        self.on_done = on_done

    def finish(self) -> None:
        """Fire ``on_done`` exactly once (swap-to-None makes repeated
        calls from overlapping failure paths safe)."""
        cb, self.on_done = self.on_done, None
        if cb is not None:
            try:
                cb()
            except Exception as e:  # noqa: BLE001 - release must not
                log.warning("on_done release hook failed: %s", e)


class ServeBatcher:
    """Coalesce requests into microbatches under a latency deadline."""

    def __init__(self, scorer, max_batch_wait_ms: float = 2.0,
                 queue_size: int = 1024, telemetry=None, tracer=None,
                 slo=None, quality=None):
        self._scorer = scorer
        self._wait_s = max(0.0, float(max_batch_wait_ms)) / 1e3
        self._tracer = tracer if tracer is not None else NULL_TRACER
        self._slo = slo
        # Training→serving skew monitor (obs.ServeSkewMonitor, None =
        # quality off): the dispatcher folds every scored request's
        # feature arrays + served scores into the live traffic sketch
        # AFTER the scores are delivered — pure observation on the
        # dispatcher thread, so responses are byte-identical with it
        # on or off (pinned by test).
        self._quality = quality
        tel = telemetry if telemetry is not None else obs.NULL
        self._c_requests = tel.counter("serve.requests")
        self._c_examples = tel.counter("serve.examples")
        self._c_batches = tel.counter("serve.batches")
        self._t_latency = tel.timer("serve.latency")
        self._g_fill = tel.gauge("serve.batch_fill")
        # Live in-flight count (accepted, scores not yet delivered):
        # the replica-side load signal the router's P2C dispatch and
        # the overload discipline reason about.
        self._g_inflight = tel.gauge("serve.inflight")
        self._q = _ClosableQueue(
            queue_size, hist=tel.depth_hist("serve.queue_depth")
        )
        # The batcher's OWN recycled per-rung staging buffers.  It must
        # not borrow the scorer's pools: those are guarded by the
        # scorer's dispatch lock, and the dispatcher fills buffers
        # BEFORE taking that lock — sharing them would let a direct
        # scorer.score() caller race the fill.
        self._pools: dict = {}
        # Fill accounting (dispatcher thread only): real examples vs
        # padded slots over every dispatched rung.
        self._slots = 0
        self._filled = 0
        # Outstanding requests, so close() can fail the ones a queue
        # cancel() discards instead of leaving clients blocked forever.
        self._outstanding: set = set()
        self._out_lock = threading.Lock()
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="tffm-serve-batcher", daemon=True
        )
        self._thread.start()

    # -- client side ---------------------------------------------------

    def submit(self, ids, vals, fields=None, rid=None,
               on_done=None) -> ScoreRequest:
        """Enqueue ``[n, max_features]`` arrays; returns the request
        future.  Raises RuntimeError once the batcher is closed.
        ``on_done`` (optional) fires exactly once when the batcher no
        longer reads the arrays — including on every rejection path of
        this call, so a pooled caller never leaks a lease.  NOTE the
        ``ascontiguousarray`` casts are no-copy for the parse pool's
        row views (C-contiguous slices of the right dtype), so the
        arrays the dispatcher reads ARE the pooled buffers."""
        req = ScoreRequest(
            np.ascontiguousarray(ids, np.int32),
            np.ascontiguousarray(vals, np.float32),
            (np.ascontiguousarray(fields, np.int32)
             if fields is not None else None),
            rid=rid,
            on_done=on_done,
        )
        with self._out_lock:
            if self._closed:
                req.finish()
                raise RuntimeError("ServeBatcher is closed")
            self._outstanding.add(req)
            self._g_inflight.set(len(self._outstanding))
        if not self._q.put(req):
            with self._out_lock:
                self._outstanding.discard(req)
                self._g_inflight.set(len(self._outstanding))
            req.finish()
            raise RuntimeError("ServeBatcher is closed")
        self._c_requests.add()
        return req

    @property
    def inflight(self) -> int:
        """Requests accepted but not yet answered (live load)."""
        with self._out_lock:
            return len(self._outstanding)

    def result(self, req: ScoreRequest,
               timeout: float = 30.0) -> np.ndarray:
        """Block until the request's scores arrive (or raise)."""
        if not req.event.wait(timeout):
            raise TimeoutError(
                f"scoring request ({req.n} examples) timed out after "
                f"{timeout}s"
            )
        if req.error is not None:
            raise req.error
        return req.scores

    def score(self, ids, vals, fields=None, timeout: float = 30.0,
              rid=None, on_done=None) -> np.ndarray:
        """submit + result in one call (the HTTP handler's path)."""
        return self.result(
            self.submit(ids, vals, fields, rid=rid, on_done=on_done),
            timeout,
        )

    @property
    def batch_fill(self) -> float:
        return self._filled / self._slots if self._slots else 0.0

    def _pool(self, b: int):
        bufs = self._pools.get(b)
        if bufs is None:
            F = self._scorer.cfg.max_features
            bufs = (
                np.zeros((b, F), np.int32),
                np.zeros((b, F), np.float32),
                np.zeros((b, F), np.int32),
            )
            self._pools[b] = bufs
        return bufs

    # -- dispatcher thread ---------------------------------------------

    def _run(self) -> None:
        max_b = self._scorer.max_rung
        pending: Optional[ScoreRequest] = None
        while True:
            first = pending if pending is not None else self._q.get()
            pending = None
            if first is _CANCELLED:
                break
            if first.rid is not None and first.t_picked is None:
                first.t_picked = time.perf_counter()
            group = [first]
            total = first.n
            deadline = time.monotonic() + self._wait_s
            while total < max_b:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                nxt = self._q.get(timeout=remaining)
                if nxt is _TIMEOUT:
                    break
                if nxt is _CANCELLED:
                    break
                if nxt.rid is not None:
                    nxt.t_picked = time.perf_counter()
                if total + nxt.n > max_b:
                    # Doesn't fit this rung: dispatch what we have and
                    # seed the next microbatch (keeps every coalesced
                    # group within one dispatch).
                    pending = nxt
                    break
                group.append(nxt)
                total += nxt.n
            self._dispatch(group, total)
        # Queue cancelled: fail whatever is still outstanding (items
        # the cancel discarded AND a pending carry-over).
        self._fail_outstanding(RuntimeError("ServeBatcher closed"))

    def _trace_request(self, g: ScoreRequest, t_d0: float,
                       t_d1: float, rung: int, total: int) -> None:
        """Emit one sampled request's replica-side spans from the
        recorded timestamps (queue wait -> coalesce -> dispatch).  The
        flow step on the rid links the chain to the router's proxy
        span and the handler's respond span."""
        args = {"rid": g.rid}
        picked = g.t_picked if g.t_picked is not None else t_d0
        self._tracer.emit(
            "serve.queue_wait", g.t0, picked - g.t0, args=args,
        )
        self._tracer.emit(
            "serve.coalesce", picked, t_d0 - picked,
            args={"rid": g.rid, "group_n": total},
        )
        self._tracer.emit(
            "serve.dispatch", t_d0, t_d1 - t_d0,
            args={"rid": g.rid, "rung": rung, "n": total},
            flow=("t", g.rid),
        )

    def _dispatch(self, group, total: int) -> None:
        scorer = self._scorer
        rung = 0
        t_d0 = time.perf_counter()
        try:
            if len(group) == 1 and total > scorer.max_rung:
                # One oversized request: the scorer chunks it itself
                # and owns the matching slot accounting.
                req = group[0]
                scores = scorer.score(req.ids, req.vals, req.fields)
                self._slots += scorer.slots_for(total)
                rung = scorer.max_rung
            else:
                rung = b = scorer.rung_for(total)
                bi, bv, bf = self._pool(b)
                pos = 0
                any_fields = any(g.fields is not None for g in group)
                for g in group:
                    bi[pos:pos + g.n] = g.ids
                    bv[pos:pos + g.n] = g.vals
                    if any_fields:
                        bf[pos:pos + g.n] = (
                            g.fields if g.fields is not None else 0
                        )
                    pos += g.n
                if pos < b:
                    bi[pos:] = 0
                    bv[pos:] = 0.0
                    if any_fields:
                        bf[pos:] = 0
                scores = scorer.score_rung(
                    bi, bv, bf if any_fields else None, b
                )
                self._slots += b
            self._filled += total
            self._g_fill.set(round(self.batch_fill, 6))
            self._c_batches.add()
            self._c_examples.add(total)
            now = time.perf_counter()
            pos = 0
            for g in group:
                g.scores = np.asarray(scores[pos:pos + g.n], np.float32)
                pos += g.n
                self._t_latency.observe(now - g.t0)
                if self._slo is not None:
                    self._slo.observe(True, now - g.t0)
                if g.rid is not None:
                    self._trace_request(g, t_d0, now, rung, total)
                with self._out_lock:
                    self._outstanding.discard(g)
                    self._g_inflight.set(len(self._outstanding))
                g.event.set()
            if self._quality is not None:
                # Skew sketching AFTER every waiter is released: the
                # request's own (unpadded) arrays and its served
                # scores — never the pool buffer, whose padded tail
                # would dilute the length/id distributions.  Its own
                # except: these requests were already ANSWERED, so a
                # sketching failure must not re-enter the outer
                # fail-the-clients handler (which would stamp errors
                # on delivered requests and double-count the SLO
                # window).
                try:
                    # ONE fold per dispatched group (concatenating the
                    # unpadded request arrays), not one per request:
                    # the dispatcher is serial, and per-request lock
                    # round-trips would add straight to the next
                    # group's queueing latency under many-small-
                    # request traffic.
                    if len(group) == 1:
                        g = group[0]
                        self._quality.observe_batch(g.ids, g.vals)
                        self._quality.observe_scores(g.scores)
                    else:
                        self._quality.observe_batch(
                            np.concatenate([g.ids for g in group]),
                            np.concatenate([g.vals for g in group]),
                        )
                        self._quality.observe_scores(
                            np.concatenate(
                                [g.scores for g in group]
                            )
                        )
                except Exception as e:  # noqa: BLE001 - observe only
                    log.warning("skew sketching failed: %s", e)
            # Last reader done (microbatch copy + quality fold both
            # read g.ids/g.vals): release pooled parse scratch.
            for g in group:
                g.finish()
        except BaseException as e:  # noqa: BLE001 - fail the CLIENTS
            log.warning("serve dispatch failed: %s", e)
            for g in group:
                g.error = e
                if self._slo is not None:
                    self._slo.observe(False)
                with self._out_lock:
                    self._outstanding.discard(g)
                    self._g_inflight.set(len(self._outstanding))
                g.event.set()
                g.finish()

    def _fail_outstanding(self, exc: BaseException) -> None:
        with self._out_lock:
            stale = list(self._outstanding)
            self._outstanding.clear()
            self._g_inflight.set(0)
        for req in stale:
            req.error = exc
            req.event.set()
            req.finish()

    def close(self) -> None:
        """Stop the dispatcher and fail any queued requests.
        Idempotent."""
        with self._out_lock:
            self._closed = True
        self._q.cancel()
        self._thread.join()
