"""Serving SLO ledger: rolling error-budget burn rate.

An SLO is a promise ("99.9% of requests answered, under 50 ms"); the
number an operator pages on is not the raw error rate but how fast the
ERROR BUDGET (1 - availability) is being spent — the burn rate.  Burn
rate 1 means the service is exactly on budget; 10 means the monthly
budget burns in ~3 days; the classic multi-window alert thresholds
(14.4 / 6 / 1) all key off this one number.

:class:`SloTracker` is the ledger both serving processes share:

- the ROUTER observes every front-door outcome (admitted request
  latency + status, sheds, no-healthy-replica 503s) — the fleet-level
  SLO;
- a single-process server's batcher observes its own per-request
  latency/errors — the same accounting without a router.

``observe(ok, latency_s)`` appends one outcome to a sliding time
window; a request is GOOD iff it was admitted, answered below 500, and
(when ``serve_slo_p99_ms`` > 0) completed within the latency
objective.  ``snapshot()`` returns the window's ``good`` / ``bad`` /
``bad_frac`` and — when ``serve_slo_availability`` > 0 — ``burn_rate``
= bad_frac / (1 - availability), and refreshes the ``serve.burn_rate``
/ ``serve.slo_bad_frac`` gauges so /metrics scrapes see the live
values.

Stdlib-only (no jax, no numpy): the router process imports it.
"""

from __future__ import annotations

import collections
import threading
import time

__all__ = ["SloTracker", "WINDOW_S"]

# Sliding-window length for the burn-rate computation.  Short enough
# that a regression shows within a minute of beats, long enough that a
# single slow request on a trickle-load service doesn't read as a
# budget fire.
WINDOW_S = 60.0


class SloTracker:
    """Sliding-window good/bad request ledger -> burn-rate gauges."""

    def __init__(self, slo_p99_ms: float, slo_availability: float,
                 telemetry=None, window_s: float = WINDOW_S):
        self.slo_p99_ms = float(slo_p99_ms)
        self.slo_availability = float(slo_availability)
        self.enabled = self.slo_p99_ms > 0 or self.slo_availability > 0
        self._window_s = float(window_s)
        self._lock = threading.Lock()
        # (timestamp, bad) outcome ledger, pruned at both ends of use.
        self._ledger: collections.deque = collections.deque()
        self._g_burn = self._g_bad_frac = None
        if telemetry is not None and self.enabled:
            if self.slo_availability > 0:
                self._g_burn = telemetry.gauge("serve.burn_rate")
            self._g_bad_frac = telemetry.gauge("serve.slo_bad_frac")

    def observe(self, ok: bool, latency_s=None,
                now: float = None) -> None:
        """One request outcome.  ``ok`` is the transport-level verdict
        (admitted and answered < 500); a latency above the objective
        demotes an otherwise-ok request to bad."""
        if not self.enabled:
            return
        bad = not ok
        if (
            not bad and self.slo_p99_ms > 0 and latency_s is not None
            and latency_s * 1e3 > self.slo_p99_ms
        ):
            bad = True
        t = time.monotonic() if now is None else now
        with self._lock:
            self._ledger.append((t, bad))
            self._prune(t)

    def _prune(self, now: float) -> None:
        horizon = now - self._window_s
        led = self._ledger
        while led and led[0][0] < horizon:
            led.popleft()

    def snapshot(self, now: float = None) -> dict:
        """Window stats (empty dict when no SLO knob is set).  Also
        refreshes the registered gauges, so building a record keeps
        /metrics' gauge spellings in step with the block keys."""
        if not self.enabled:
            return {}
        t = time.monotonic() if now is None else now
        with self._lock:
            self._prune(t)
            total = len(self._ledger)
            bad = sum(1 for _, b in self._ledger if b)
        bad_frac = bad / total if total else 0.0
        out = {
            "slo_window_s": self._window_s,
            "slo_good": total - bad,
            "slo_bad": bad,
            "slo_bad_frac": round(bad_frac, 6),
        }
        if self.slo_p99_ms > 0:
            out["slo_p99_ms"] = self.slo_p99_ms
        if self.slo_availability > 0:
            budget = 1.0 - self.slo_availability
            burn = bad_frac / budget if budget > 0 else 0.0
            out["slo_availability"] = self.slo_availability
            out["burn_rate"] = round(burn, 4)
            if self._g_burn is not None:
                self._g_burn.set(round(burn, 4))
        if self._g_bad_frac is not None:
            self._g_bad_frac.set(round(bad_frac, 6))
        return out
