"""Scale-out serving: N shared-nothing replicas behind a small router.

One serve process tops out at one box's worth of a single Python
runtime; ROADMAP direction 3 wants throughput that scales with
processes and p99 that degrades gracefully under spike traffic.  This
module is that layer, all stdlib + numpy (the router process never
imports jax — replicas own the devices):

- :class:`ReplicaManager` — spawns ``serve_replicas`` replica serve
  subprocesses (each the existing scorer/batcher/server stack on its
  own OS-assigned port, announced on stdout) and owns their teardown
  (terminate/wait, kill after a grace period).
- :class:`ServeRouter` — an HTTP front door on ``serve_port`` doing
  **power-of-two-choices** dispatch: pick two healthy replicas at
  random, send to the one with fewer router-tracked in-flight requests.
  Health comes from the replicas' existing ``/healthz`` surface (plus
  process liveness and proxy failures): an unhealthy replica is
  EVICTED from routing and readmitted when it answers again; a request
  caught on a dying replica retries transparently on another.
- **Overload discipline** — admission control with a per-request
  deadline budget (``serve_shed_deadline_ms``): projected queue delay
  is in-flight requests over the measured completion rate (Little's
  law), and a request that could not be answered inside the budget is
  shed with a fast ``429`` + ``Retry-After`` instead of queuing — p99
  of ADMITTED requests stays bounded instead of collapsing.
  ``serve.shed`` / ``serve.inflight`` / per-replica routed counters
  ride the serve block and ``/metrics``.
- **Canary promotion** (``serve_canary``) — replicas are launched with
  their manifest watcher OFF; the router watches
  ``serve_manifest.json`` itself, reloads ONE replica on a new
  checkpoint (the replica keeps the replaced params restorable),
  shadow-scores a recent traffic sample against a baseline replica,
  compares the two score distributions via ``tools/report.py
  --compare``, and only then rolls the reload across the fleet — or
  rolls the canary back.  Every swap stays the scorer's
  reference-swap, so no request is ever served a torn table.

Transport is pass-through: the router proxies ``POST /score`` (libsvm
text) and ``POST /score_bin`` (the binary frame, serve/wire.py)
verbatim, reusing kept-alive connections to each replica.
"""

from __future__ import annotations

import collections
import http.client
import json
import logging
import os
import random
import re
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Optional

import numpy as np

from fast_tffm_tpu import obs
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.obs.status import (
    ObsHTTPServer, PooledHTTPServer, QuietHandler, render_prometheus,
)
from fast_tffm_tpu.obs.trace import NULL_TRACER, Tracer
from fast_tffm_tpu.serve import wire
from fast_tffm_tpu.serve.slo import SloTracker
from fast_tffm_tpu.train import manifest

log = logging.getLogger(__name__)

__all__ = [
    "FleetHandle", "Replica", "ReplicaManager", "ServeRouter",
    "serve_fleet", "start_fleet",
]

# The replica CLI announces its bound port with this exact line
# (server.serve_forever's print) — the manager parses it instead of
# pre-allocating ports, so there is no bind race.
_PORT_RE = re.compile(r"serving on [^\s:]+:(\d+)")

# Consecutive /healthz failures before the health loop evicts (proxy
# failures evict immediately — they already cost a request a retry).
_FAIL_EVICT = 2

# Largest request body the canary shadow-scoring ring retains (bounds
# the ring at maxlen * this many bytes).
_SAMPLE_BODY_MAX = 256 << 10


class Replica:
    """Router-side state for one backend replica.

    ``proc`` is the managed subprocess (None for an externally-run
    backend, e.g. tests pointing the router at fake replicas).
    ``inflight``/``routed``/``healthy``/``fails``/``quarantined`` are
    guarded by the router's lock.  A QUARANTINED replica is one whose
    params can no longer be trusted (a rejected canary whose rollback
    failed): alive is not enough to readmit it — the health loop skips
    it until a later successful promotion reloads it onto a vetted
    checkpoint.
    """

    __slots__ = ("index", "host", "port", "proc", "inflight", "routed",
                 "healthy", "fails", "quarantined", "respawn_fails",
                 "respawn_pending", "next_respawn_t")

    def __init__(self, index: int, host: str, port: int, proc=None):
        self.index = index
        self.host = host
        self.port = port
        self.proc = proc
        self.inflight = 0
        self.routed = 0
        self.healthy = True
        self.fails = 0
        self.quarantined = False
        # Respawn state (health-loop thread only): the in-flight
        # _ReplicaProc of a relaunch, consecutive failed relaunches,
        # and the earliest monotonic time the next attempt may start.
        self.respawn_fails = 0
        self.respawn_pending = None
        self.next_respawn_t = 0.0

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"


class _ReplicaProc:
    """One spawned replica subprocess: stdout port announcement +
    ordered teardown.  The stdout pipe is drained for the process's
    lifetime so a chatty child can never block on a full pipe."""

    def __init__(self, index: int, cmd: list, env: dict):
        self.index = index
        self.port: Optional[int] = None
        self.ready = threading.Event()
        self.proc = subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE,
        )
        self._thread = threading.Thread(
            target=self._drain, name=f"tffm-replica-stdout-{index}",
            daemon=True,
        )
        self._thread.start()

    def _drain(self) -> None:
        try:
            for raw in self.proc.stdout:
                if self.port is None:
                    m = _PORT_RE.search(raw.decode("utf-8", "replace"))
                    if m:
                        self.port = int(m.group(1))
                        self.ready.set()
        finally:
            self.ready.set()  # EOF with no port = startup failure

    def close(self, grace_s: float = 10.0) -> None:
        """Terminate and reap; SIGKILL after the grace period.  A
        replica that already died (or was killed externally) just gets
        reaped."""
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(grace_s)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()
        else:
            self.proc.wait()
        self._thread.join()
        if self.proc.stdout is not None:
            self.proc.stdout.close()


# CLI overrides the fleet launcher consumes itself (or forces per
# replica) rather than passing through.  trace_file and
# serve_trace_sample are fleet-level: the launcher re-renders the
# trace path with a per-replica suffix (N replicas dumping to ONE
# path would clobber each other) and pins replica self-sampling OFF —
# the ROUTER is the fleet's front door and owns the sampling decision;
# a replica that also sampled its own proxied traffic would mint
# partial chains with no router half.
_NO_PASSTHROUGH = {
    "serve_replicas", "serve_port", "serve_host", "serve_canary",
    "serve_poll_secs", "metrics_file", "trace_file",
    "serve_trace_sample", "alert_rules", "serve_capture_file",
}

# Respawn backoff (ROADMAP direction-3 leftover): a died MANAGED
# replica relaunches after min(_RESPAWN_CAP_S, _RESPAWN_BASE_S * 2^k)
# where k counts consecutive failed relaunches (a replica that dies
# before announcing its port).  The first death respawns immediately;
# a crash-looping one backs off to the cap.
_RESPAWN_BASE_S = 1.0
_RESPAWN_CAP_S = 30.0


def _passthrough_flags(overrides: Optional[dict]) -> list:
    """Re-render the router invocation's CLI overrides as replica
    flags, so ``serve --replicas 2 --serve_table_dtype int8`` means the
    same thing on every replica as it would single-process."""
    args: list = []
    for key, val in sorted((overrides or {}).items()):
        if key in _NO_PASSTHROUGH or val is None:
            continue
        if key == "telemetry":
            if val is False:
                args.append("--no_telemetry")
            continue
        if key == "resource_metrics":
            if val is False:
                args.append("--no_resource_metrics")
            continue
        if key == "trace_file":
            args += ["--trace", str(val)]
            continue
        flag = "--" + key
        if val is True:
            args.append(flag)
        elif val is not False:
            args += [flag, str(val)]
    return args


def _replica_command(cfg: FmConfig, cfg_path: str, index: int,
                     overrides: Optional[dict]) -> list:
    cmd = [
        sys.executable, "-m", "fast_tffm_tpu.cli", "serve", cfg_path,
        # --replicas 0 pins the child single-process even when the cfg
        # file itself sets serve_replicas (a fleet must never recurse),
        # and --no_serve_canary force-clears an INI serve_canary so the
        # child doesn't trip its own canary-requires-a-fleet
        # validation.
        "--replicas", "0", "--no_serve_canary",
        "--serve_port", "0", "--serve_host", "127.0.0.1",
        # Canary mode turns the replicas' own manifest watchers OFF —
        # the router drives every swap; otherwise replicas self-swap on
        # their usual poll cadence.
        "--serve_poll_secs",
        "0" if cfg.serve_canary else str(cfg.serve_poll_secs),
        # The router owns trace sampling (it mints the ids and stamps
        # them onto proxied requests); a replica that also sampled its
        # own traffic would emit router-less partial chains.  Forced
        # here so an INI-configured serve_trace_sample can't leak into
        # the children (same neutralization as --no_serve_canary).
        "--serve_trace_sample", "0",
        # The router owns the alert watchdog too: fleet rules (burn
        # rate, shed fraction, staleness) evaluate against ROUTER
        # heartbeats.  A replica re-reading the same rules would
        # self-halt on an action=halt breach — and the respawn policy
        # would relaunch it into an endless warm-up/halt/respawn loop.
        "--alert_rules", "",
    ]
    if cfg.metrics_file:
        # One JSONL stream per process: N replicas appending to the
        # router's configured path would interleave into garbage.
        cmd += ["--metrics_file", f"{cfg.metrics_file}.replica{index}"]
    if cfg.trace_file:
        # Same one-file-per-process rule for traces; report.py
        # --serve-trace merges the family back onto one timeline.
        cmd += ["--trace", f"{cfg.trace_file}.replica{index}"]
    if cfg.serve_capture_file:
        # Same one-file-per-process rule for TFC1 captures: replicas
        # score (and therefore capture) the traffic, each into its own
        # rotating file; tools/replay.py re-drives any of them.
        cmd += [
            "--serve_capture_file",
            f"{cfg.serve_capture_file}.replica{index}",
        ]
    return cmd + _passthrough_flags(overrides)


class ReplicaManager:
    """Spawn and own ``cfg.serve_replicas`` shared-nothing replica
    serve subprocesses.

    Each replica is the full existing stack (``run_tffm.py serve`` on
    an OS-assigned port); startup blocks until every replica announces
    its port (which serve_forever prints only after the ladder is
    warmed, so a ready replica is a WARM replica).  ``close()`` tears
    every process down terminate->wait->kill.
    """

    def __init__(self, cfg: FmConfig, cfg_path: str,
                 overrides: Optional[dict] = None,
                 startup_timeout_s: float = 300.0):
        if cfg.serve_replicas < 2:
            raise ValueError(
                "ReplicaManager needs serve_replicas >= 2 (a single "
                "process does not want a router)"
            )
        env = os.environ.copy()
        # Children launch via `-m fast_tffm_tpu.cli`; the parent may
        # have found the package through script-dir sys.path injection,
        # which the environment does not inherit.
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ))
        env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
        self._cfg = cfg
        self._cfg_path = cfg_path
        self._overrides = overrides
        self._env = env
        self._lock = threading.Lock()
        self._closed = False
        self._procs: list = []
        self.replicas: list = []
        try:
            for i in range(cfg.serve_replicas):
                cmd = _replica_command(cfg, cfg_path, i, overrides)
                self._procs.append(_ReplicaProc(i, cmd, env))
            deadline = time.time() + startup_timeout_s
            for rp in self._procs:
                rp.ready.wait(max(0.0, deadline - time.time()))
                if rp.port is None:
                    raise RuntimeError(
                        f"replica {rp.index} did not announce a "
                        f"serving port within {startup_timeout_s:.0f}s "
                        f"(exit code {rp.proc.poll()})"
                    )
                self.replicas.append(
                    Replica(rp.index, "127.0.0.1", rp.port, proc=rp.proc)
                )
            log.info(
                "replica fleet up: %s",
                ", ".join(f"#{r.index}@{r.address}" for r in
                          self.replicas),
            )
        except BaseException:
            self.close()
            raise

    def respawn(self, index: int):
        """Relaunch replica ``index``'s subprocess (the respawn policy,
        ROADMAP direction-3 leftover).  The dead predecessor is reaped
        first; the fresh :class:`_ReplicaProc` is adopted into
        ``_procs`` immediately (the manager owns every child it ever
        spawned — lint rule TL006's reachable-teardown invariant) and
        returned NON-blocking: the router's health loop polls its
        ``ready``/``port`` and re-points the :class:`Replica` at the
        announced port.  Returns None once the manager is closed (a
        teardown racing a death must not spawn an orphan)."""
        with self._lock:
            if self._closed:
                return None
            old = self._procs[index]
            try:
                old.close(grace_s=0.0)  # already dead: reap + join
            except Exception as e:  # noqa: BLE001 - reap best-effort
                log.warning("replica %d reap failed: %s", index, e)
            cmd = _replica_command(
                self._cfg, self._cfg_path, index, self._overrides
            )
            fresh = _ReplicaProc(index, cmd, self._env)
            self._procs[index] = fresh
        log.info("respawning replica %d (pid %d)", index,
                 fresh.proc.pid)
        return fresh

    def close(self) -> None:
        with self._lock:
            self._closed = True
            procs, self._procs = self._procs, []
        for rp in procs:
            try:
                rp.close()
            except Exception as e:  # noqa: BLE001 - teardown best-effort
                log.warning("replica %d teardown failed: %s",
                            rp.index, e)


class _ProxyError(Exception):
    """A connection-level failure talking to a replica (the replica is
    presumed dying; the request is retried elsewhere)."""


class ServeRouter:
    """The HTTP front door: P2C dispatch + overload discipline + the
    canary promotion protocol, over any list of :class:`Replica`."""

    def __init__(self, port: int, replicas, cfg: FmConfig,
                 telemetry=None, writer=None, host: str = "127.0.0.1",
                 health_secs: float = 0.5,
                 manifest_seen: Optional[dict] = None,
                 proxy_timeout_s: float = 30.0, tracer=None,
                 sampler=None, respawner=None):
        self.cfg = cfg
        tel = telemetry if telemetry is not None else obs.NULL
        self._tel = tel
        self._c_requests = tel.counter("serve.router_requests")
        self._c_shed = tel.counter("serve.shed")
        self._c_evictions = tel.counter("serve.evictions")
        self._c_readmissions = tel.counter("serve.readmissions")
        self._c_retries = tel.counter("serve.retries")
        self._c_promotions = tel.counter("serve.canary_promotions")
        self._c_rollbacks = tel.counter("serve.canary_rollbacks")
        self._c_respawns = tel.counter("serve.respawns")
        self._c_scrape_errors = tel.counter("serve.scrape_errors")
        self._g_inflight = tel.gauge("serve.inflight")
        self._t_proxy = tel.timer("serve.proxy")
        self._t_scrape = tel.timer("serve.fleet_scrape")
        self._writer = writer
        self._replicas = list(replicas)
        self._lock = threading.Lock()
        self._rng = random.Random(0xF00D)
        self._deadline_s = cfg.serve_shed_deadline_ms / 1e3
        self._proxy_timeout_s = proxy_timeout_s
        # Distributed tracing: the router is the fleet's front door,
        # so it owns the sampling decision and the request-id mint;
        # tracer disabled (no trace_file) = the shared no-op.
        self._tracer = tracer if tracer is not None else NULL_TRACER
        self._sampler = sampler if sampler is not None else (
            wire.RequestSampler(
                cfg.serve_trace_sample, enabled=self._tracer.enabled,
                tag="rt",
            )
        )
        # SLO ledger: every front-door outcome (admitted latency +
        # status, sheds, no-replica 503s) -> rolling burn rate.
        self._slo = SloTracker(
            cfg.serve_slo_p99_ms, cfg.serve_slo_availability,
            telemetry=tel,
        )
        # Respawn policy: relaunch died MANAGED replicas (callable
        # index -> _ReplicaProc-shaped handle, normally
        # ReplicaManager.respawn).  None = the historical evict-only
        # behavior (unmanaged host:port replicas always are).
        self._respawner = respawner
        # Completion timestamps inside a sliding window: the measured
        # service rate the admission budget divides by (Little's law).
        self._rate_window_s = 1.0
        self._completions: collections.deque = collections.deque()
        # Idle kept-alive connections per replica index.
        self._conns: dict = {r.index: [] for r in self._replicas}
        # Latest per-replica /status scrape: index -> (wall time,
        # serve block dict).  The health loop doubles as the fleet
        # metrics scraper; /metrics re-exposes these as fleet
        # aggregates + per-replica labeled series.
        self._scrapes: dict = {}
        # Recent request bodies, the canary shadow-scoring sample.
        self._sample: collections.deque = collections.deque(maxlen=32)
        self._health_secs = max(0.05, float(health_secs))
        self.step = int((manifest_seen or {}).get("step", 0))
        self._seen = manifest_seen
        self._t0 = time.time()
        self._stop = threading.Event()
        router = self

        class Handler(QuietHandler):
            def do_POST(self) -> None:  # noqa: N802 - http.server API
                router._c_requests.add()
                path = self.path.partition("?")[0]
                if path == "/incident":
                    # Manual forensic dump (same admin route as the
                    # replicas' own endpoints, but this one captures
                    # the ROUTER's rings: fleet scrapes, shed state).
                    bb = router.blackbox
                    self._post_incident(
                        self.path.partition("?")[2],
                        bb.incident if bb is not None else None,
                    )
                    return
                if path not in ("/score", "/score_bin"):
                    self._send(404, b"not found\n", "text/plain")
                    return
                want = "text" if path == "/score" else "bin"
                if cfg.serve_transport not in (want, "both"):
                    self._send(
                        404, f"transport {want!r} disabled "
                             f"(serve_transport="
                             f"{cfg.serve_transport})\n".encode(),
                        "text/plain",
                    )
                    return
                body = self._read_body(wire.MAX_BODY_BYTES)
                if body is None:
                    return  # error response already sent
                ctype = self.headers.get(
                    "Content-Type",
                    "text/plain" if want == "text"
                    else "application/octet-stream",
                )
                # Request id: client-supplied X-Request-Id always
                # propagates and echoes; otherwise the sampling coin
                # flip decides whether to mint one.  An unsampled
                # id-less request does NO id work and proxies
                # byte-identical bodies (pinned by test).
                rid = self.headers.get("X-Request-Id")
                if rid is not None and not wire.valid_request_id(rid):
                    rid = None
                if rid is None and router._sampler.sample():
                    rid = router._sampler.mint()
                status, data, rctype, headers = router._handle(
                    path, body, ctype, rid=rid
                )
                if rid is not None:
                    headers = dict(headers or {})
                    headers["X-Request-Id"] = rid
                # The body was fully consumed above, so even an error
                # status is keep-alive-safe — and a shedding router
                # MUST keep connections open (closing them turns every
                # 429 into a client reconnect under peak load).
                self._send(
                    status, data, rctype, headers=headers,
                    keep_alive=True,
                )

            def do_GET(self) -> None:  # noqa: N802 - http.server API
                path = self.path.partition("?")[0]
                if path == "/metrics":
                    # /metrics grows per-replica labeled series the
                    # flat record rendering cannot express, so the
                    # router renders it itself.
                    self._send(
                        200, router._render_metrics().encode(),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                    return
                if self._get_observability(path, router._build):
                    return
                self._send(404, b"not found\n", "text/plain")

        # Every attribute a handler can touch must exist BEFORE the
        # HTTP thread starts: on a fixed, well-known port a retrying
        # client can connect the instant the socket binds.  The
        # blackbox and alert engine are mounted by start_fleet AFTER
        # construction (they want the run header / router heartbeat),
        # so they start as None here.
        self.blackbox = None
        self.alert_engine = None
        self._closed = False
        self._canary_thread = (
            threading.Thread(
                target=self._canary_loop, name="tffm-router-canary",
                daemon=True,
            )
            if cfg.serve_canary else None
        )
        self._health_thread = threading.Thread(
            target=self._health_loop, name="tffm-router-health",
            daemon=True,
        )
        # The router front door shares the serve endpoints' pooled
        # accept path (serve_http_threads > 0, the default); 0 keeps
        # thread-per-connection.  Two plain assignments so the
        # lifecycle lint sees both constructor bindings.
        if cfg.serve_http_threads > 0:
            self._httpd = PooledHTTPServer(
                (host, port), Handler,
                pool_size=cfg.serve_http_threads,
                acceptors=cfg.serve_http_acceptors,
            )
        else:
            self._httpd = ObsHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="tffm-router-http",
            daemon=True,
        )
        self._thread.start()
        self._health_thread.start()
        if self._canary_thread is not None:
            self._canary_thread.start()

    # -- dispatch --------------------------------------------------------

    def _completion_rate(self) -> float:
        """Completions/sec over the sliding window (caller holds the
        lock)."""
        now = time.perf_counter()
        dq = self._completions
        while dq and now - dq[0] > self._rate_window_s:
            dq.popleft()
        return len(dq) / self._rate_window_s

    def _admit(self):
        """(replica, None) when admitted — in-flight already counted —
        or (None, "shed" | "none")."""
        with self._lock:
            healthy = [r for r in self._replicas if r.healthy]
            if not healthy:
                return None, "none"
            total = sum(r.inflight for r in healthy)
            if self._deadline_s > 0:
                # Deadline-budget admission: with I requests in flight
                # completing at X/sec, a new arrival waits ~I/X before
                # its turn (Little's law).  The 2-per-replica floor
                # always admits at trickle load, where the rate window
                # has nothing in it.
                floor = 2 * len(healthy)
                if total >= floor:
                    rate = self._completion_rate()
                    if rate > 0 and (total + 1) / rate > self._deadline_s:
                        return None, "shed"
            if len(healthy) >= 2:
                a, b = self._rng.sample(healthy, 2)
                rep = a if a.inflight <= b.inflight else b
            else:
                rep = healthy[0]
            rep.inflight += 1
            rep.routed += 1
            self._g_inflight.set(total + 1)
            return rep, None

    def _pick_retry(self, exclude):
        """Re-pick after a proxy failure (least-loaded healthy replica
        other than the failed one); counts the in-flight slot."""
        with self._lock:
            healthy = [
                r for r in self._replicas
                if r.healthy and r is not exclude
            ]
            if not healthy:
                return None
            rep = min(healthy, key=lambda r: r.inflight)
            rep.inflight += 1
            rep.routed += 1
            return rep

    def _dec(self, rep: Replica) -> None:
        with self._lock:
            rep.inflight = max(0, rep.inflight - 1)
            self._g_inflight.set(
                sum(r.inflight for r in self._replicas)
            )

    def _handle(self, path: str, body: bytes, ctype: str, rid=None):
        """Route one scoring request; returns (status, body, ctype,
        headers-or-None) for the front handler to send.  ``rid`` (a
        sampled or client-supplied request id) propagates to the
        replica and opens the request's router-side span chain."""
        t_admit = time.perf_counter()
        rep, why = self._admit()
        traced = rid is not None and self._tracer.enabled
        if traced:
            # The admit/shed decision: tiny, but it is where a shed
            # request's chain ENDS — an operator tracing a 429 sees
            # the decision, not silence.
            self._tracer.emit(
                "serve.admit", t_admit,
                time.perf_counter() - t_admit,
                args={
                    "rid": rid,
                    "decision": why or "admit",
                    "replica": rep.index if rep is not None else -1,
                },
            )
        if rep is None:
            self._slo.observe(False)
            if why == "shed":
                self._c_shed.add()
                return (
                    429,
                    b"overloaded: projected queue delay exceeds "
                    b"serve_shed_deadline_ms; retry\n",
                    "text/plain", {"Retry-After": "1"},
                )
            return 503, b"no healthy replica\n", "text/plain", None
        t0 = time.perf_counter()
        while True:
            try:
                status, data, rctype = self._forward(
                    rep, path, body, ctype, rid=rid, traced=traced,
                )
                break
            except _ProxyError as e:
                # The replica died under the request: evict it and
                # retry the (idempotent) scoring request elsewhere —
                # a SIGKILLed replica costs its in-flight requests one
                # retry, not an error.
                self._dec(rep)
                self._evict(rep, f"proxy failure: {e}")
                self._c_retries.add()
                rep = self._pick_retry(exclude=rep)
                if rep is None:
                    self._slo.observe(False)
                    return (503, b"no healthy replica\n", "text/plain",
                            None)
        self._dec(rep)
        now = time.perf_counter()
        self._t_proxy.observe(now - t0)
        # SLO verdict: admitted and answered below 500 is transport-ok
        # (a 4xx is the client's malformed request, not lost
        # availability); the latency objective can still demote it.
        self._slo.observe(status < 500, now - t0)
        if traced:
            # The proxy span opens the cross-process flow ("s"): the
            # replica's serve.dispatch steps it, serve.respond ends it.
            self._tracer.emit(
                "serve.proxy", t0, now - t0,
                args={"rid": rid, "replica": rep.index,
                      "status": status},
                flow=("s", rid),
            )
        with self._lock:
            self._completions.append(now)
        if (
            self._canary_thread is not None and status == 200
            and len(body) <= _SAMPLE_BODY_MAX
        ):
            # Shadow-scoring sample; the size guard bounds the ring at
            # maxlen * _SAMPLE_BODY_MAX bytes (bodies can legally be
            # up to the 64 MiB cap).
            self._sample.append((path, body))
        return status, data, rctype, None

    # -- replica connections ----------------------------------------------

    def _conn_acquire(self, rep: Replica):
        """(connection, reused) — a pooled kept-alive connection when
        one is idle, else a fresh one."""
        with self._lock:
            pool = self._conns.get(rep.index) or []
            if pool:
                return pool.pop(), True
        return http.client.HTTPConnection(
            rep.host, rep.port, timeout=self._proxy_timeout_s
        ), False

    def _conn_release(self, rep: Replica, conn) -> None:
        with self._lock:
            if rep.healthy:
                self._conns.setdefault(rep.index, []).append(conn)
                return
        conn.close()

    def _forward(self, rep: Replica, path: str, body: bytes,
                 ctype: str, rid=None, traced: bool = False):
        """One proxied POST.  A failure on a REUSED connection retries
        once on a fresh one (an idle kept-alive socket the replica
        timed out is stale, not a dead replica); a fresh-connection
        failure raises _ProxyError.

        ``rid`` propagates to the replica as the ``X-Request-Id``
        header; a TRACED ``/score_bin`` request additionally carries it
        as the frame's flags-bit-1 trailer (the binary transport's
        documented spelling) — an untraced frame proxies byte-identical
        to what the client sent."""
        headers = {"Content-Type": ctype}
        if rid is not None:
            headers["X-Request-Id"] = rid
            if traced and path == "/score_bin":
                body = wire.with_bin_request_id(body, rid)
        for attempt in (0, 1):
            conn, reused = self._conn_acquire(rep)
            if attempt and reused:
                # Second pass must be a real liveness probe.
                conn.close()
                conn, reused = http.client.HTTPConnection(
                    rep.host, rep.port, timeout=self._proxy_timeout_s
                ), False
            try:
                conn.request(
                    "POST", path, body=body, headers=headers,
                )
                resp = conn.getresponse()
                data = resp.read()
            except (OSError, http.client.HTTPException) as e:
                conn.close()
                if reused:
                    continue  # stale pooled socket; try fresh
                raise _ProxyError(f"{type(e).__name__}: {e}") from e
            rctype = resp.getheader("Content-Type") or "text/plain"
            if resp.will_close or resp.status >= 400:
                conn.close()
            else:
                self._conn_release(rep, conn)
            return resp.status, data, rctype
        raise _ProxyError("unreachable")  # pragma: no cover

    # -- health ------------------------------------------------------------

    def _evict(self, rep: Replica, reason: str,
               quarantine: bool = False) -> None:
        with self._lock:
            if quarantine:
                rep.quarantined = True
            if not rep.healthy:
                return
            rep.healthy = False
            rep.fails = 0
            stale = self._conns.get(rep.index) or []
            self._conns[rep.index] = []
        for conn in stale:
            conn.close()
        self._c_evictions.add()
        log.warning(
            "replica %d (%s) EVICTED from routing: %s",
            rep.index, rep.address, reason,
        )

    def _readmit(self, rep: Replica) -> None:
        with self._lock:
            # A quarantined replica is ALIVE but serving unvetted
            # params (rejected canary, failed rollback): answering
            # /healthz must not put it back in rotation — only a later
            # successful promotion clears the quarantine.
            if rep.healthy or rep.quarantined:
                return
            rep.healthy = True
            rep.fails = 0
            # Back in service resets the respawn backoff: the next
            # death is a fresh incident, not attempt k+1 of this one.
            rep.respawn_fails = 0
        self._c_readmissions.add()
        log.info("replica %d (%s) readmitted to routing",
                 rep.index, rep.address)

    def _probe_health(self, rep: Replica) -> bool:
        try:
            with urllib.request.urlopen(
                f"http://{rep.address}/healthz", timeout=1.0
            ) as resp:
                return resp.status == 200
        except (urllib.error.URLError, OSError):
            return False

    def _health_loop(self) -> None:
        while not self._stop.wait(self._health_secs):
            for rep in self._replicas:
                if self._stop.is_set():
                    return
                if rep.respawn_pending is not None:
                    self._respawn_poll(rep)
                if rep.proc is not None and rep.proc.poll() is not None:
                    self._evict(
                        rep, f"process exited {rep.proc.poll()}"
                    )
                    self._respawn_step(rep)
                    continue
                if self._probe_health(rep):
                    with self._lock:
                        rep.fails = 0
                    self._readmit(rep)
                else:
                    with self._lock:
                        rep.fails += 1
                        dead = rep.healthy and rep.fails >= _FAIL_EVICT
                    if dead:
                        self._evict(
                            rep,
                            f"{rep.fails} consecutive /healthz "
                            "failures",
                        )
            self._scrape_fleet()

    # -- respawn policy ----------------------------------------------------

    def _respawn_step(self, rep: Replica) -> None:
        """Relaunch a died MANAGED replica (health-loop thread).  The
        launch is non-blocking — _respawn_poll watches the fresh
        process's port announcement over subsequent ticks — and each
        attempt backs off exponentially (capped) until a readmission
        resets the counter.  Unmanaged host:port replicas (proc None)
        and routers without a respawner keep the historical evict-only
        behavior."""
        if (
            self._respawner is None or rep.proc is None
            or rep.respawn_pending is not None
        ):
            return
        now = time.monotonic()
        if now < rep.next_respawn_t:
            return
        rep.next_respawn_t = now + min(
            _RESPAWN_CAP_S, _RESPAWN_BASE_S * (2 ** rep.respawn_fails)
        )
        rep.respawn_fails += 1
        try:
            pending = self._respawner(rep.index)
        except Exception as e:  # noqa: BLE001 - retry at the backoff
            log.warning("replica %d respawn launch failed: %s",
                        rep.index, e)
            return
        if pending is None:  # manager closing; no orphan spawned
            return
        rep.respawn_pending = pending
        self._c_respawns.add()

    def _respawn_poll(self, rep: Replica) -> None:
        """Adopt a pending respawn once its port is announced (the
        replica prints it only after the ladder is warm, so an adopted
        replica is a WARM replica); a relaunch that died without
        announcing counts against the backoff and retries."""
        pending = rep.respawn_pending
        if not pending.ready.is_set():
            return
        rep.respawn_pending = None
        if pending.port is None:
            log.warning(
                "respawned replica %d died before announcing a port "
                "(exit %s); next attempt in %.0fs",
                rep.index, pending.proc.poll(),
                max(0.0, rep.next_respawn_t - time.monotonic()),
            )
            return
        with self._lock:
            rep.port = pending.port
            rep.proc = pending.proc
            # Any pooled connection still points at the OLD port.
            stale = self._conns.get(rep.index) or []
            self._conns[rep.index] = []
        for conn in stale:
            conn.close()
        log.info(
            "replica %d respawned on %s (pid %s); awaiting the health "
            "loop's readmission", rep.index, rep.address, rep.pid,
        )

    # -- fleet metrics scrape ----------------------------------------------

    def _scrape_fleet(self) -> None:
        """Pull each healthy replica's /status serve block (the health
        loop doubles as the fleet metrics scraper).  Results feed the
        fleet aggregates + per-replica labeled series on the router's
        /metrics; a failed scrape keeps the previous block and lets its
        staleness age (``fleet_scrape_age_max_s`` is the alert
        signal)."""
        with self._t_scrape.time():
            for rep in self._replicas:
                if self._stop.is_set():
                    return
                with self._lock:
                    healthy = rep.healthy
                if not healthy:
                    continue
                try:
                    with urllib.request.urlopen(
                        f"http://{rep.address}/status", timeout=2.0
                    ) as resp:
                        doc = json.loads(resp.read())
                except (urllib.error.URLError, OSError, ValueError):
                    self._c_scrape_errors.add()
                    continue
                block = doc.get("serve")
                if isinstance(block, dict):
                    with self._lock:
                        self._scrapes[rep.index] = (time.time(), block)

    # -- canary promotion ---------------------------------------------------

    def _admin(self, rep: Replica, route: str) -> dict:
        """POST an admin route on a replica; returns the JSON doc.
        Raises _ProxyError on connection failure and ValueError on a
        4xx/5xx (the replica refused — e.g. an unservable checkpoint)."""
        status, data, _ = self._forward(
            rep, route, b"", "application/octet-stream"
        )
        if status != 200:
            raise ValueError(
                f"replica {rep.index} {route} answered {status}: "
                f"{data[:200].decode(errors='replace')}"
            )
        return json.loads(data)

    def _canary_loop(self) -> None:
        poll = max(0.05, self.cfg.serve_poll_secs)
        while not self._stop.wait(poll):
            try:
                self._canary_check()
            except Exception as e:  # noqa: BLE001 - retry next poll
                log.warning(
                    "canary watcher: promotion attempt failed (%s); "
                    "will retry next poll", e,
                )

    def _canary_check(self) -> None:
        man = manifest.read_manifest(self.cfg.model_file)
        if man is None or man == self._seen:
            return
        with self._lock:
            healthy = [r for r in self._replicas if r.healthy]
        if len(healthy) < 2:
            # Promotion needs a canary AND a baseline; retry the next
            # poll (the manifest stays un-baselined, so an evicted
            # replica coming back resumes promotion).
            log.warning(
                "canary: new checkpoint published but only %d healthy "
                "replica(s); deferring promotion", len(healthy),
            )
            return
        canary, baseline = healthy[0], healthy[1]
        try:
            # keep_prev=1 opens the replica's rollback window (and
            # anchors it across a retried reload); the fleet-roll and
            # quarantine-recovery reloads below stay plain — they are
            # promoted immediately, so retaining a standby table would
            # only pin memory.
            step = int(self._admin(
                canary, "/reload?keep_prev=1"
            ).get("step", 0))
        except ValueError as e:
            # The replica REFUSED the checkpoint (dtype/shape/format
            # contradiction): permanent for this manifest — baseline
            # it like the single-process watcher does instead of
            # re-reading a multi-GB table every poll.
            log.warning(
                "canary reload refused (%s); keeping the current "
                "fleet, will pick up the next save", e,
            )
            self._seen = man
            return
        ok, detail = self._shadow_compare(canary, baseline, step)
        if ok:
            try:
                self._admin(canary, "/promote")
            except ValueError as e:  # pragma: no cover - defensive
                log.warning("canary promote failed: %s", e)
            promoted = 1
            for rep in healthy[1:]:
                try:
                    self._admin(rep, "/reload")
                    self._admin(rep, "/promote")
                    promoted += 1
                except (ValueError, _ProxyError) as e:
                    log.warning(
                        "rolling promotion: replica %d failed to "
                        "reload (%s) — it keeps serving the OLD "
                        "params until the next manifest", rep.index, e,
                    )
            self._c_promotions.add()
            self.step = step
            log.info(
                "canary promotion to step %d complete (%d/%d "
                "replicas; %s)", step, promoted, len(healthy), detail,
            )
            # A quarantined replica (rejected canary whose rollback
            # failed) can rejoin ONLY by landing on a vetted
            # checkpoint: reload it onto the step the fleet just
            # promoted, then clear the quarantine so the health loop
            # may readmit it.
            with self._lock:
                quarantined = [
                    r for r in self._replicas if r.quarantined
                ]
            for rep in quarantined:
                try:
                    self._admin(rep, "/reload")
                    self._admin(rep, "/promote")
                    with self._lock:
                        rep.quarantined = False
                    log.info(
                        "quarantined replica %d reloaded onto the "
                        "promoted step %d; eligible for readmission",
                        rep.index, step,
                    )
                except (ValueError, _ProxyError) as e:
                    log.warning(
                        "quarantined replica %d could not reload the "
                        "promoted checkpoint (%s); it stays out of "
                        "routing", rep.index, e,
                    )
        else:
            try:
                self._admin(canary, "/rollback")
            except (ValueError, _ProxyError) as e:
                log.warning(
                    "canary ROLLBACK FAILED on replica %d (%s) — "
                    "QUARANTINING it rather than serving an unvetted "
                    "table (a later successful promotion reloads and "
                    "readmits it)", canary.index, e,
                )
                self._evict(
                    canary,
                    "rollback failed after a rejected canary",
                    quarantine=True,
                )
            self._c_rollbacks.add()
            log.warning(
                "canary REJECTED at step %d: %s — rolled back; this "
                "manifest is baselined (republish to retry)",
                step, detail,
            )
        self._seen = man

    def _shadow_score(self, rep: Replica, path: str, body: bytes):
        """Replay one sampled request directly against a replica;
        returns its scores (list of float) or None on failure."""
        try:
            status, data, _ = self._forward(
                rep, path,
                body,
                "text/plain" if path == "/score"
                else "application/octet-stream",
            )
        except _ProxyError:
            return None
        if status != 200:
            return None
        try:
            if path == "/score":
                return [float(tok) for tok in data.split()]
            return [float(s) for s in wire.decode_bin_response(data)]
        except ValueError:
            return None

    def _gate_scale(self, scores) -> np.ndarray:
        """Scores on a ratio-stable scale for the drift gate.

        Logistic serving already answers sigmoid probabilities in
        (0, 1), where a ratio IS relative drift.  mse serving answers
        RAW scores, which routinely sit near (or straddle) zero — a
        raw-ratio gate there turns negligible absolute drift into
        huge ratios (or inf, or sign-flipped ratios), spuriously
        rejecting canaries.  Squashing raw scores through the same
        sigmoid gives a bounded positive scale that is monotone in
        the score, so real drift still moves every quantile.
        """
        arr = np.asarray(scores, np.float64)
        if self.cfg.loss_type != "logistic":
            arr = 1.0 / (1.0 + np.exp(-arr))
        return arr

    @staticmethod
    def _dist_stats(scores: np.ndarray) -> dict:
        return {
            "metric": "canary_shadow_scores",
            "score_n": int(len(scores)),
            "score_mean": float(np.mean(scores)),
            "score_std": float(np.std(scores)),
            "score_p10": float(np.percentile(scores, 10)),
            "score_p50": float(np.percentile(scores, 50)),
            "score_p90": float(np.percentile(scores, 90)),
        }

    def _shadow_compare(self, canary: Replica, baseline: Replica,
                        step: int):
        """Shadow-score the sampled traffic on the canary and a
        baseline replica and judge the two score distributions with
        ``tools/report.py --compare`` (exit 2 = drifted -> reject).
        Returns (ok, detail)."""
        sample = list(self._sample)
        if not sample:
            return True, ("no traffic sample collected; promoting "
                          "without a shadow compare")
        c_scores: list = []
        b_scores: list = []
        for path, body in sample:
            sc = self._shadow_score(canary, path, body)
            sb = self._shadow_score(baseline, path, body)
            if sc is None or sb is None or len(sc) != len(sb):
                continue
            c_scores.extend(sc)
            b_scores.extend(sb)
        if not c_scores:
            return True, ("shadow replay produced no comparable "
                          "scores; promoting")
        stats_b = self._dist_stats(self._gate_scale(b_scores))
        stats_c = self._dist_stats(self._gate_scale(c_scores))
        out_dir = os.path.join(
            os.path.abspath(self.cfg.model_file), "canary_compare",
            f"step_{step}",
        )
        os.makedirs(out_dir, exist_ok=True)
        path_b = os.path.join(out_dir, "baseline.json")
        path_c = os.path.join(out_dir, "canary.json")
        with open(path_b, "w") as f:
            json.dump(stats_b, f, indent=1)
        with open(path_c, "w") as f:
            json.dump(stats_c, f, indent=1)
        report = os.path.join(
            os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))
            )),
            "tools", "report.py",
        )
        if os.path.exists(report):
            proc = subprocess.run(
                [sys.executable, report, "--compare", path_b, path_c,
                 "--threshold", "default=0.05"],
                capture_output=True, timeout=60,
            )
            tail = proc.stdout.decode(errors="replace").strip(
            ).splitlines()[-1:] or [""]
            detail = (
                f"report.py --compare exit {proc.returncode} "
                f"({tail[0]}; artifacts in {out_dir})"
            )
            # Exit 0 = within threshold.  Exit 2 = drift.  Anything
            # else is a tooling failure — reject rather than promote
            # an unjudged table.
            return proc.returncode == 0, detail
        # Degraded in-process gate (report.py missing from this
        # install): same keys, same 5% ratio rule, flagged loudly.
        log.warning(
            "canary compare: %s not found; using the in-process "
            "ratio gate", report,
        )
        for key in ("score_mean", "score_p10", "score_p50",
                    "score_p90"):
            va, vb = stats_b[key], stats_c[key]
            if va == 0 and vb == 0:
                continue
            ratio = vb / va if va else float("inf")
            if not 0.95 <= ratio <= 1.05:
                return False, (
                    f"in-process gate: {key} ratio {ratio:.3f} "
                    f"(artifacts in {out_dir})"
                )
        return True, f"in-process gate passed (artifacts in {out_dir})"

    # -- record / metrics ----------------------------------------------------

    # Scraped serve-block keys re-exposed per replica as labeled
    # series on the router's /metrics (plus the scrape's own age).
    _REPLICA_SERIES = (
        ("requests", "tffm_serve_replica_requests_total", "counter"),
        ("qps", "tffm_serve_replica_qps", "gauge"),
        ("p50_ms", "tffm_serve_replica_p50_ms", "gauge"),
        ("p99_ms", "tffm_serve_replica_p99_ms", "gauge"),
        ("batch_fill", "tffm_serve_replica_batch_fill", "gauge"),
        ("steady_compiles", "tffm_serve_replica_steady_compiles",
         "gauge"),
        ("skew_psi_max", "tffm_serve_replica_skew_psi_max", "gauge"),
    )

    # The serving fleet's merge over scraped replica serve blocks —
    # sums for the monotonic counters and rates, a request-weighted
    # mean for p50, MAX for the tails (a merged p99 cannot be computed
    # from per-replica percentiles; the max is the honest conservative
    # bound), a plain mean for batch fill, and the training→serving
    # skew PSIs MAX-merged under their SAME key names (a per-replica
    # PSI is already a distribution distance; the fleet's worst one is
    # the aggregate — a mean would dilute a single skewed replica
    # N-fold) with skew_examples summed (mass, not distance).  The
    # semantics live in obs.merge_blocks, shared with the training
    # fleet plane (obs/fleet.py) so the two cannot drift.
    _FLEET_SPEC = obs.MergeSpec(
        sums=("requests", "examples", "batches", "qps",
              "steady_compiles", "recompiles_unexpected"),
        weighted=("p50_ms",),
        weight_key="requests",
        tails=("p95_ms", "p99_ms", "max_ms"),
        means=("batch_fill",),
        max_same=("skew_psi_values", "skew_psi_lengths",
                  "skew_psi_ids", "skew_psi_scores", "skew_psi_max"),
        sum_same_int=("skew_examples",),
        prefix="fleet_",
        count_key="replicas_scraped",
        age_key="fleet_scrape_age_max_s",
    )

    def _fleet_aggregates(self, per: list, scrapes: dict,
                          now: float) -> dict:
        """Fleet-level aggregates over the latest per-replica /status
        scrapes, folded per ``_FLEET_SPEC`` (including the scrape
        staleness age the alert plane watches)."""
        return obs.merge_blocks(
            ServeRouter._FLEET_SPEC,
            [scrapes[p["index"]] for p in per if p["index"] in scrapes],
            now,
        )

    def _build(self, kind: str = "status") -> dict:
        now = time.time()
        wall = max(now - self._t0, 1e-9)
        # SLO gauges refresh BEFORE the snapshot so one scrape's gauge
        # spellings agree with its serve-block keys.
        slo_block = self._slo.snapshot()
        snap = self._tel.snapshot()
        counters = snap.get("counters") or {}
        timers = snap.get("timers") or {}
        with self._lock:
            scrapes = dict(self._scrapes)
            per = [
                {
                    "index": r.index, "port": r.port, "pid": r.pid,
                    "healthy": r.healthy,
                    "quarantined": r.quarantined,
                    "respawning": r.respawn_pending is not None,
                    "inflight": r.inflight, "routed": r.routed,
                }
                for r in self._replicas
            ]
        for p in per:
            scraped = scrapes.get(p["index"])
            if scraped is not None:
                t, b = scraped
                p["scrape_age_s"] = round(now - t, 3)
                for key in ("qps", "p50_ms", "p99_ms", "requests",
                            "batch_fill", "steady_compiles"):
                    if key in b:
                        p[key] = b[key]
        requests = int(counters.get("serve.router_requests", 0))
        shed = int(counters.get("serve.shed", 0))
        block = {
            "requests": requests,
            "shed": shed,
            "shed_frac": round(shed / requests, 6) if requests else 0.0,
            "qps": round(requests / wall, 2),
            "inflight": sum(p["inflight"] for p in per),
            "replicas": len(per),
            "replicas_healthy": sum(1 for p in per if p["healthy"]),
            "evictions": int(counters.get("serve.evictions", 0)),
            "readmissions": int(
                counters.get("serve.readmissions", 0)
            ),
            "retries": int(counters.get("serve.retries", 0)),
            "respawns": int(counters.get("serve.respawns", 0)),
            "canary_promotions": int(
                counters.get("serve.canary_promotions", 0)
            ),
            "canary_rollbacks": int(
                counters.get("serve.canary_rollbacks", 0)
            ),
            "per_replica": per,  # /status detail; non-numeric, so the
        }                        # Prometheus rendering skips it
        block.update(self._fleet_aggregates(per, scrapes, now))
        block.update(slo_block)
        proxy = timers.get("serve.proxy") or {}
        for key in ("mean_ms", "p50_ms", "p95_ms", "p99_ms", "max_ms"):
            if key in proxy:
                block[key] = proxy[key]
        rec = {
            "record": kind,
            "time": now,
            "elapsed": round(wall, 3),
            "step": self.step,
            "serve": block,
            "stages": snap,
        }
        if self.cfg.resource_metrics:
            rec["resource"] = obs.basic_block(self._t0)
        if self.alert_engine is not None:
            # Armed-rule states for /status and the per-rule
            # tffm_alert_active gauges.
            rec["alerts"] = self.alert_engine.active_snapshot()
        if self._tracer.enabled:
            rec["trace_dropped_events"] = self._tracer.dropped_events
        return rec

    def _render_metrics(self) -> str:
        record = self._build("status")
        per = record["serve"]["per_replica"]
        lines = [render_prometheus(record).rstrip("\n")]
        lines.extend(obs.labeled_lines(
            "tffm_serve_replica_healthy", "gauge",
            [({"replica": p["index"], "port": p["port"]},
              1 if p["healthy"] else 0) for p in per],
        ))
        lines.extend(obs.labeled_lines(
            "tffm_serve_replica_inflight", "gauge",
            [({"replica": p["index"]}, p["inflight"]) for p in per],
        ))
        lines.extend(obs.labeled_lines(
            "tffm_serve_replica_routed_total", "counter",
            [({"replica": p["index"]}, p["routed"]) for p in per],
        ))
        # Fleet scrape re-exposition: the per-replica serve blocks the
        # health loop pulled, as labeled series — one router scrape
        # sees the whole fleet.
        for key, name, mtype in self._REPLICA_SERIES:
            lines.extend(obs.labeled_lines(name, mtype, [
                ({"replica": p["index"]}, p[key])
                for p in per if key in p
            ]))
        lines.extend(obs.labeled_lines(
            "tffm_serve_replica_scrape_age_s", "gauge",
            [({"replica": p["index"]}, p["scrape_age_s"])
             for p in per if "scrape_age_s" in p],
        ))
        return "\n".join(lines) + "\n"

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        self._httpd.shutdown()
        self._thread.join()
        self._httpd.server_close()
        self._health_thread.join()
        if self._canary_thread is not None:
            self._canary_thread.join()
        with self._lock:
            pools = list(self._conns.values())
            self._conns = {}
        for pool in pools:
            for conn in pool:
                conn.close()


class FleetHandle:
    """One running router + replica fleet; ``close()`` tears it down in
    order (router stops routing, replicas terminate, final record
    written, router trace dumped)."""

    def __init__(self, cfg, manager, router, telemetry, writer,
                 heartbeat, tracer=None, alert_engine=None):
        self.cfg = cfg
        self.manager = manager
        self.router = router
        self.replicas = router._replicas
        self.telemetry = telemetry
        self.port = router.port
        self.alert_engine = alert_engine
        self.exception: Optional[BaseException] = None
        self._writer = writer
        self._heartbeat = heartbeat
        self._tracer = tracer
        self._closed = False

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._heartbeat is not None:
            self._heartbeat.close()
        self.router.close()
        if self.manager is not None:
            self.manager.close()
        blackbox = self.router.blackbox
        if self._writer is not None or blackbox is not None:
            try:
                final = self.router._build("final")
                if self.exception is not None:
                    # Crash-truthful final (an alert halt, a fatal
                    # mount error): the stream names why the fleet
                    # stopped, same contract as the trainer's.
                    final["exception"] = type(self.exception).__name__
                    final["exception_msg"] = str(self.exception)
                if self._writer is not None:
                    self._writer.write(final)
                if blackbox is not None:
                    blackbox.observe_record(final)
            except Exception as e:  # noqa: BLE001 - teardown best-effort
                log.warning("router final record write failed: %s", e)
        # Crash-truthful bundle, dumped BEFORE the writer closes so
        # the incident manifest still reaches the metrics stream.
        if (
            blackbox is not None
            and self.exception is not None
            and not isinstance(self.exception, KeyboardInterrupt)
        ):
            blackbox.incident("crash_" + type(self.exception).__name__)
        if self._writer is not None:
            self._writer.close()
        if self._tracer is not None and self._tracer.enabled:
            try:
                n = self._tracer.dump(self.cfg.trace_file)
                self._tracer.close()
                log.info(
                    "router trace written to %s (%d events)",
                    self.cfg.trace_file, n,
                )
            except Exception as e:  # noqa: BLE001 - teardown best-effort
                log.warning("router trace dump failed: %s", e)


def start_fleet(cfg: FmConfig, cfg_path: str,
                overrides: Optional[dict] = None,
                port: Optional[int] = None) -> FleetHandle:
    """Spawn the replica fleet and mount the router over it.

    ``port`` overrides ``cfg.serve_port`` (tests pass 0).  The manifest
    baseline is captured BEFORE the replicas spawn, so a checkpoint
    published during their warmup still looks new to the canary
    watcher's first poll.
    """
    writer = (
        obs.JsonlWriter(cfg.metrics_file) if cfg.metrics_file else None
    )
    telemetry = obs.Telemetry(enabled=cfg.telemetry)
    # The router's half of the distributed trace (admit + proxy spans,
    # flow arrows keyed on the request id); replicas write their own
    # trace_file.replicaN halves and report.py --serve-trace re-joins
    # the family.
    tracer = (
        Tracer(
            enabled=True, process_name="router",
            rotate_events=cfg.trace_rotate_events,
            rotate_path=cfg.trace_file or None,
        )
        if cfg.trace_file else NULL_TRACER
    )
    manifest_seen = manifest.read_manifest(cfg.model_file)
    manager = None
    router = None
    heartbeat = None
    alert_engine = None
    try:
        manager = ReplicaManager(cfg, cfg_path, overrides=overrides)
        router = ServeRouter(
            cfg.serve_port if port is None else port,
            manager.replicas, cfg, telemetry=telemetry, writer=writer,
            host=cfg.serve_host, manifest_seen=manifest_seen,
            tracer=tracer, respawner=manager.respawn,
        )
        run_header = {
                "record": "run_header",
                "mode": "serve_router",
                "time": time.time(),
                "model_file": cfg.model_file,
                "resume_step": router.step,
                "batch_size": cfg.batch_size,
                "telemetry": cfg.telemetry,
                "heartbeat_secs": cfg.heartbeat_secs,
                "serve_replicas": cfg.serve_replicas,
                "serve_shed_deadline_ms": cfg.serve_shed_deadline_ms,
                "serve_canary": cfg.serve_canary,
                "serve_transport": cfg.serve_transport,
                "serve_poll_secs": cfg.serve_poll_secs,
                "serve_trace_sample": cfg.serve_trace_sample,
                "serve_slo_p99_ms": cfg.serve_slo_p99_ms,
                "serve_slo_availability": cfg.serve_slo_availability,
                # Front-end shape knobs (shared with the replicas via
                # the relayed config): the fleet's accept path must be
                # reconstructable from any metrics stream.
                "serve_parse_mode": cfg.serve_parse_mode,
                "serve_http_threads": cfg.serve_http_threads,
                "serve_http_acceptors": cfg.serve_http_acceptors,
                "serve_request_queue_size":
                    ObsHTTPServer.request_queue_size,
                "alert_rules": cfg.alert_rules,
                "trace_file": cfg.trace_file,
                "replica_ports": [r.port for r in manager.replicas],
                "blackbox": cfg.blackbox,
        }
        if writer is not None:
            writer.write(run_header)
        # The router's incident flight recorder: its rings hold the
        # fleet-level heartbeats (per-replica scrape detail included),
        # so an alert bundle names the unhealthy replica without any
        # replica-side digging.
        if cfg.blackbox:
            router.blackbox = obs.Blackbox(
                cfg.incident_dir
                or os.path.join(cfg.model_file, "incidents"),
                suffix="router",
                run_header=run_header,
                metrics_render=router._render_metrics,
                trace_tail_fn=(
                    tracer.tail if tracer.enabled else None
                ),
                writer=writer,
                telemetry=telemetry,
            )
        # Alert watchdog on the ROUTER's heartbeat: the serve-signal
        # rules (shed_frac, burn_rate, evictions,
        # fleet_scrape_age_max_s, ...) evaluate against every fleet
        # heartbeat; action=halt arms the engine and serve_fleet stops
        # the fleet (crash-truthful final).  Breaches also reach the
        # blackbox, which dumps a forensic bundle.
        if cfg.alert_rules:
            alert_engine = obs.AlertEngine(
                obs.parse_rules(cfg.alert_rules), writer=writer,
                on_alert=(
                    router.blackbox.on_alert
                    if router.blackbox is not None else None
                ),
            )
            router.alert_engine = alert_engine

        def heartbeat_build():
            rec = router._build("heartbeat")
            if rec is not None:
                # Ring BEFORE the alert engine observes, so an alert-
                # triggered bundle contains the breaching record.
                if router.blackbox is not None:
                    router.blackbox.observe_record(rec)
                if alert_engine is not None:
                    alert_engine.observe(rec)
            return rec

        if cfg.heartbeat_secs > 0:
            heartbeat = obs.Heartbeat(
                cfg.heartbeat_secs, heartbeat_build, writer=writer,
            )
    except BaseException:
        # A failed mount must not leak replica processes or threads.
        if router is not None:
            router.close()
        if manager is not None:
            manager.close()
        if writer is not None:
            writer.close()
        if tracer is not NULL_TRACER:
            tracer.close()
        raise
    log.info(
        "router listening on %s:%d over %d replicas (POST /score, "
        "/score_bin; GET /metrics, /status, /healthz)",
        cfg.serve_host, router.port, len(manager.replicas),
    )
    return FleetHandle(cfg, manager, router, telemetry, writer,
                       heartbeat, tracer=tracer,
                       alert_engine=alert_engine)


def serve_fleet(cfg: FmConfig, cfg_path: str,
                overrides: Optional[dict] = None) -> int:
    """CLI entry for ``run_tffm.py serve <cfg> --replicas N``: route
    until interrupted.  SIGTERM and SIGINT both tear the fleet down —
    the replica subprocesses must never outlive their router.  An
    armed ``action: halt`` alert rule (burn rate, shed fraction,
    staleness) stops the fleet with a crash-truthful final record —
    the serving spelling of the training watchdog's halt contract."""
    handle = start_fleet(cfg, cfg_path, overrides=overrides)
    print(
        f"routing on {cfg.serve_host}:{handle.port} across "
        f"{len(handle.replicas)} replica(s)", flush=True,
    )

    def _sigterm(signum, frame):  # pragma: no cover - signal path
        raise KeyboardInterrupt

    prev = signal.signal(signal.SIGTERM, _sigterm)
    try:
        obs.run_until_halt(handle.alert_engine)
    except KeyboardInterrupt:
        log.info("interrupted; shutting down the fleet")
    except obs.AlertHaltError as e:
        log.error("HALT: %s", e)
        handle.exception = e
        handle.close()
        signal.signal(signal.SIGTERM, prev)
        return 1
    finally:
        if not handle._closed:
            handle.close()
        signal.signal(signal.SIGTERM, prev)
    return 0
