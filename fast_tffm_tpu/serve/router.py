"""Scale-out serving: N shared-nothing replicas behind a small router.

One serve process tops out at one box's worth of a single Python
runtime; ROADMAP direction 3 wants throughput that scales with
processes and p99 that degrades gracefully under spike traffic.  This
module is that layer, all stdlib + numpy (the router process never
imports jax — replicas own the devices):

- :class:`ReplicaManager` — spawns ``serve_replicas`` replica serve
  subprocesses (each the existing scorer/batcher/server stack on its
  own OS-assigned port, announced on stdout) and owns their teardown
  (terminate/wait, kill after a grace period).
- :class:`ServeRouter` — an HTTP front door on ``serve_port`` doing
  **power-of-two-choices** dispatch: pick two healthy replicas at
  random, send to the one with fewer router-tracked in-flight requests.
  Health comes from the replicas' existing ``/healthz`` surface (plus
  process liveness and proxy failures): an unhealthy replica is
  EVICTED from routing and readmitted when it answers again; a request
  caught on a dying replica retries transparently on another.
- **Overload discipline** — admission control with a per-request
  deadline budget (``serve_shed_deadline_ms``): projected queue delay
  is in-flight requests over the measured completion rate (Little's
  law), and a request that could not be answered inside the budget is
  shed with a fast ``429`` + ``Retry-After`` instead of queuing — p99
  of ADMITTED requests stays bounded instead of collapsing.
  ``serve.shed`` / ``serve.inflight`` / per-replica routed counters
  ride the serve block and ``/metrics``.
- **Canary promotion** (``serve_canary``) — replicas are launched with
  their manifest watcher OFF; the router watches
  ``serve_manifest.json`` itself, reloads ONE replica on a new
  checkpoint (the replica keeps the replaced params restorable),
  shadow-scores a recent traffic sample against a baseline replica,
  compares the two score distributions via ``tools/report.py
  --compare``, and only then rolls the reload across the fleet — or
  rolls the canary back.  Every swap stays the scorer's
  reference-swap, so no request is ever served a torn table.

Transport is pass-through: the router proxies ``POST /score`` (libsvm
text) and ``POST /score_bin`` (the binary frame, serve/wire.py)
verbatim, reusing kept-alive connections to each replica.
"""

from __future__ import annotations

import collections
import http.client
import json
import logging
import os
import random
import re
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Optional

import numpy as np

from fast_tffm_tpu import obs
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.obs.status import (
    ObsHTTPServer, QuietHandler, render_prometheus,
)
from fast_tffm_tpu.serve import wire
from fast_tffm_tpu.train import manifest

log = logging.getLogger(__name__)

__all__ = [
    "FleetHandle", "Replica", "ReplicaManager", "ServeRouter",
    "serve_fleet", "start_fleet",
]

# The replica CLI announces its bound port with this exact line
# (server.serve_forever's print) — the manager parses it instead of
# pre-allocating ports, so there is no bind race.
_PORT_RE = re.compile(r"serving on [^\s:]+:(\d+)")

# Consecutive /healthz failures before the health loop evicts (proxy
# failures evict immediately — they already cost a request a retry).
_FAIL_EVICT = 2

# Largest request body the canary shadow-scoring ring retains (bounds
# the ring at maxlen * this many bytes).
_SAMPLE_BODY_MAX = 256 << 10


class Replica:
    """Router-side state for one backend replica.

    ``proc`` is the managed subprocess (None for an externally-run
    backend, e.g. tests pointing the router at fake replicas).
    ``inflight``/``routed``/``healthy``/``fails``/``quarantined`` are
    guarded by the router's lock.  A QUARANTINED replica is one whose
    params can no longer be trusted (a rejected canary whose rollback
    failed): alive is not enough to readmit it — the health loop skips
    it until a later successful promotion reloads it onto a vetted
    checkpoint.
    """

    __slots__ = ("index", "host", "port", "proc", "inflight", "routed",
                 "healthy", "fails", "quarantined")

    def __init__(self, index: int, host: str, port: int, proc=None):
        self.index = index
        self.host = host
        self.port = port
        self.proc = proc
        self.inflight = 0
        self.routed = 0
        self.healthy = True
        self.fails = 0
        self.quarantined = False

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"


class _ReplicaProc:
    """One spawned replica subprocess: stdout port announcement +
    ordered teardown.  The stdout pipe is drained for the process's
    lifetime so a chatty child can never block on a full pipe."""

    def __init__(self, index: int, cmd: list, env: dict):
        self.index = index
        self.port: Optional[int] = None
        self.ready = threading.Event()
        self.proc = subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE,
        )
        self._thread = threading.Thread(
            target=self._drain, name=f"tffm-replica-stdout-{index}",
            daemon=True,
        )
        self._thread.start()

    def _drain(self) -> None:
        try:
            for raw in self.proc.stdout:
                if self.port is None:
                    m = _PORT_RE.search(raw.decode("utf-8", "replace"))
                    if m:
                        self.port = int(m.group(1))
                        self.ready.set()
        finally:
            self.ready.set()  # EOF with no port = startup failure

    def close(self, grace_s: float = 10.0) -> None:
        """Terminate and reap; SIGKILL after the grace period.  A
        replica that already died (or was killed externally) just gets
        reaped."""
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(grace_s)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()
        else:
            self.proc.wait()
        self._thread.join()
        if self.proc.stdout is not None:
            self.proc.stdout.close()


# CLI overrides the fleet launcher consumes itself (or forces per
# replica) rather than passing through.
_NO_PASSTHROUGH = {
    "serve_replicas", "serve_port", "serve_host", "serve_canary",
    "serve_poll_secs", "metrics_file",
}


def _passthrough_flags(overrides: Optional[dict]) -> list:
    """Re-render the router invocation's CLI overrides as replica
    flags, so ``serve --replicas 2 --serve_table_dtype int8`` means the
    same thing on every replica as it would single-process."""
    args: list = []
    for key, val in sorted((overrides or {}).items()):
        if key in _NO_PASSTHROUGH or val is None:
            continue
        if key == "telemetry":
            if val is False:
                args.append("--no_telemetry")
            continue
        if key == "resource_metrics":
            if val is False:
                args.append("--no_resource_metrics")
            continue
        if key == "trace_file":
            args += ["--trace", str(val)]
            continue
        flag = "--" + key
        if val is True:
            args.append(flag)
        elif val is not False:
            args += [flag, str(val)]
    return args


def _replica_command(cfg: FmConfig, cfg_path: str, index: int,
                     overrides: Optional[dict]) -> list:
    cmd = [
        sys.executable, "-m", "fast_tffm_tpu.cli", "serve", cfg_path,
        # --replicas 0 pins the child single-process even when the cfg
        # file itself sets serve_replicas (a fleet must never recurse),
        # and --no_serve_canary force-clears an INI serve_canary so the
        # child doesn't trip its own canary-requires-a-fleet
        # validation.
        "--replicas", "0", "--no_serve_canary",
        "--serve_port", "0", "--serve_host", "127.0.0.1",
        # Canary mode turns the replicas' own manifest watchers OFF —
        # the router drives every swap; otherwise replicas self-swap on
        # their usual poll cadence.
        "--serve_poll_secs",
        "0" if cfg.serve_canary else str(cfg.serve_poll_secs),
    ]
    if cfg.metrics_file:
        # One JSONL stream per process: N replicas appending to the
        # router's configured path would interleave into garbage.
        cmd += ["--metrics_file", f"{cfg.metrics_file}.replica{index}"]
    return cmd + _passthrough_flags(overrides)


class ReplicaManager:
    """Spawn and own ``cfg.serve_replicas`` shared-nothing replica
    serve subprocesses.

    Each replica is the full existing stack (``run_tffm.py serve`` on
    an OS-assigned port); startup blocks until every replica announces
    its port (which serve_forever prints only after the ladder is
    warmed, so a ready replica is a WARM replica).  ``close()`` tears
    every process down terminate->wait->kill.
    """

    def __init__(self, cfg: FmConfig, cfg_path: str,
                 overrides: Optional[dict] = None,
                 startup_timeout_s: float = 300.0):
        if cfg.serve_replicas < 2:
            raise ValueError(
                "ReplicaManager needs serve_replicas >= 2 (a single "
                "process does not want a router)"
            )
        env = os.environ.copy()
        # Children launch via `-m fast_tffm_tpu.cli`; the parent may
        # have found the package through script-dir sys.path injection,
        # which the environment does not inherit.
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ))
        env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
        self._procs: list = []
        self.replicas: list = []
        try:
            for i in range(cfg.serve_replicas):
                cmd = _replica_command(cfg, cfg_path, i, overrides)
                self._procs.append(_ReplicaProc(i, cmd, env))
            deadline = time.time() + startup_timeout_s
            for rp in self._procs:
                rp.ready.wait(max(0.0, deadline - time.time()))
                if rp.port is None:
                    raise RuntimeError(
                        f"replica {rp.index} did not announce a "
                        f"serving port within {startup_timeout_s:.0f}s "
                        f"(exit code {rp.proc.poll()})"
                    )
                self.replicas.append(
                    Replica(rp.index, "127.0.0.1", rp.port, proc=rp.proc)
                )
            log.info(
                "replica fleet up: %s",
                ", ".join(f"#{r.index}@{r.address}" for r in
                          self.replicas),
            )
        except BaseException:
            self.close()
            raise

    def close(self) -> None:
        for rp in self._procs:
            try:
                rp.close()
            except Exception as e:  # noqa: BLE001 - teardown best-effort
                log.warning("replica %d teardown failed: %s",
                            rp.index, e)
        self._procs = []


class _ProxyError(Exception):
    """A connection-level failure talking to a replica (the replica is
    presumed dying; the request is retried elsewhere)."""


class ServeRouter:
    """The HTTP front door: P2C dispatch + overload discipline + the
    canary promotion protocol, over any list of :class:`Replica`."""

    def __init__(self, port: int, replicas, cfg: FmConfig,
                 telemetry=None, writer=None, host: str = "127.0.0.1",
                 health_secs: float = 0.5,
                 manifest_seen: Optional[dict] = None,
                 proxy_timeout_s: float = 30.0):
        self.cfg = cfg
        tel = telemetry if telemetry is not None else obs.NULL
        self._tel = tel
        self._c_requests = tel.counter("serve.router_requests")
        self._c_shed = tel.counter("serve.shed")
        self._c_evictions = tel.counter("serve.evictions")
        self._c_readmissions = tel.counter("serve.readmissions")
        self._c_retries = tel.counter("serve.retries")
        self._c_promotions = tel.counter("serve.canary_promotions")
        self._c_rollbacks = tel.counter("serve.canary_rollbacks")
        self._g_inflight = tel.gauge("serve.inflight")
        self._t_proxy = tel.timer("serve.proxy")
        self._writer = writer
        self._replicas = list(replicas)
        self._lock = threading.Lock()
        self._rng = random.Random(0xF00D)
        self._deadline_s = cfg.serve_shed_deadline_ms / 1e3
        self._proxy_timeout_s = proxy_timeout_s
        # Completion timestamps inside a sliding window: the measured
        # service rate the admission budget divides by (Little's law).
        self._rate_window_s = 1.0
        self._completions: collections.deque = collections.deque()
        # Idle kept-alive connections per replica index.
        self._conns: dict = {r.index: [] for r in self._replicas}
        # Recent request bodies, the canary shadow-scoring sample.
        self._sample: collections.deque = collections.deque(maxlen=32)
        self._health_secs = max(0.05, float(health_secs))
        self.step = int((manifest_seen or {}).get("step", 0))
        self._seen = manifest_seen
        self._t0 = time.time()
        self._stop = threading.Event()
        router = self

        class Handler(QuietHandler):
            def do_POST(self) -> None:  # noqa: N802 - http.server API
                router._c_requests.add()
                path = self.path.partition("?")[0]
                if path not in ("/score", "/score_bin"):
                    self._send(404, b"not found\n", "text/plain")
                    return
                want = "text" if path == "/score" else "bin"
                if cfg.serve_transport not in (want, "both"):
                    self._send(
                        404, f"transport {want!r} disabled "
                             f"(serve_transport="
                             f"{cfg.serve_transport})\n".encode(),
                        "text/plain",
                    )
                    return
                body = self._read_body(wire.MAX_BODY_BYTES)
                if body is None:
                    return  # error response already sent
                ctype = self.headers.get(
                    "Content-Type",
                    "text/plain" if want == "text"
                    else "application/octet-stream",
                )
                status, data, rctype, headers = router._handle(
                    path, body, ctype
                )
                # The body was fully consumed above, so even an error
                # status is keep-alive-safe — and a shedding router
                # MUST keep connections open (closing them turns every
                # 429 into a client reconnect under peak load).
                self._send(
                    status, data, rctype, headers=headers,
                    keep_alive=True,
                )

            def do_GET(self) -> None:  # noqa: N802 - http.server API
                path = self.path.partition("?")[0]
                if path == "/metrics":
                    # /metrics grows per-replica labeled series the
                    # flat record rendering cannot express, so the
                    # router renders it itself.
                    self._send(
                        200, router._render_metrics().encode(),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                    return
                if self._get_observability(path, router._build):
                    return
                self._send(404, b"not found\n", "text/plain")

        # Every attribute a handler can touch must exist BEFORE the
        # HTTP thread starts: on a fixed, well-known port a retrying
        # client can connect the instant the socket binds.
        self._closed = False
        self._canary_thread = (
            threading.Thread(
                target=self._canary_loop, name="tffm-router-canary",
                daemon=True,
            )
            if cfg.serve_canary else None
        )
        self._health_thread = threading.Thread(
            target=self._health_loop, name="tffm-router-health",
            daemon=True,
        )
        self._httpd = ObsHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="tffm-router-http",
            daemon=True,
        )
        self._thread.start()
        self._health_thread.start()
        if self._canary_thread is not None:
            self._canary_thread.start()

    # -- dispatch --------------------------------------------------------

    def _completion_rate(self) -> float:
        """Completions/sec over the sliding window (caller holds the
        lock)."""
        now = time.perf_counter()
        dq = self._completions
        while dq and now - dq[0] > self._rate_window_s:
            dq.popleft()
        return len(dq) / self._rate_window_s

    def _admit(self):
        """(replica, None) when admitted — in-flight already counted —
        or (None, "shed" | "none")."""
        with self._lock:
            healthy = [r for r in self._replicas if r.healthy]
            if not healthy:
                return None, "none"
            total = sum(r.inflight for r in healthy)
            if self._deadline_s > 0:
                # Deadline-budget admission: with I requests in flight
                # completing at X/sec, a new arrival waits ~I/X before
                # its turn (Little's law).  The 2-per-replica floor
                # always admits at trickle load, where the rate window
                # has nothing in it.
                floor = 2 * len(healthy)
                if total >= floor:
                    rate = self._completion_rate()
                    if rate > 0 and (total + 1) / rate > self._deadline_s:
                        return None, "shed"
            if len(healthy) >= 2:
                a, b = self._rng.sample(healthy, 2)
                rep = a if a.inflight <= b.inflight else b
            else:
                rep = healthy[0]
            rep.inflight += 1
            rep.routed += 1
            self._g_inflight.set(total + 1)
            return rep, None

    def _pick_retry(self, exclude):
        """Re-pick after a proxy failure (least-loaded healthy replica
        other than the failed one); counts the in-flight slot."""
        with self._lock:
            healthy = [
                r for r in self._replicas
                if r.healthy and r is not exclude
            ]
            if not healthy:
                return None
            rep = min(healthy, key=lambda r: r.inflight)
            rep.inflight += 1
            rep.routed += 1
            return rep

    def _dec(self, rep: Replica) -> None:
        with self._lock:
            rep.inflight = max(0, rep.inflight - 1)
            self._g_inflight.set(
                sum(r.inflight for r in self._replicas)
            )

    def _handle(self, path: str, body: bytes, ctype: str):
        """Route one scoring request; returns (status, body, ctype,
        headers-or-None) for the front handler to send."""
        rep, why = self._admit()
        if rep is None:
            if why == "shed":
                self._c_shed.add()
                return (
                    429,
                    b"overloaded: projected queue delay exceeds "
                    b"serve_shed_deadline_ms; retry\n",
                    "text/plain", {"Retry-After": "1"},
                )
            return 503, b"no healthy replica\n", "text/plain", None
        t0 = time.perf_counter()
        while True:
            try:
                status, data, rctype = self._forward(
                    rep, path, body, ctype
                )
                break
            except _ProxyError as e:
                # The replica died under the request: evict it and
                # retry the (idempotent) scoring request elsewhere —
                # a SIGKILLed replica costs its in-flight requests one
                # retry, not an error.
                self._dec(rep)
                self._evict(rep, f"proxy failure: {e}")
                self._c_retries.add()
                rep = self._pick_retry(exclude=rep)
                if rep is None:
                    return (503, b"no healthy replica\n", "text/plain",
                            None)
        self._dec(rep)
        now = time.perf_counter()
        self._t_proxy.observe(now - t0)
        with self._lock:
            self._completions.append(now)
        if (
            self._canary_thread is not None and status == 200
            and len(body) <= _SAMPLE_BODY_MAX
        ):
            # Shadow-scoring sample; the size guard bounds the ring at
            # maxlen * _SAMPLE_BODY_MAX bytes (bodies can legally be
            # up to the 64 MiB cap).
            self._sample.append((path, body))
        return status, data, rctype, None

    # -- replica connections ----------------------------------------------

    def _conn_acquire(self, rep: Replica):
        """(connection, reused) — a pooled kept-alive connection when
        one is idle, else a fresh one."""
        with self._lock:
            pool = self._conns.get(rep.index) or []
            if pool:
                return pool.pop(), True
        return http.client.HTTPConnection(
            rep.host, rep.port, timeout=self._proxy_timeout_s
        ), False

    def _conn_release(self, rep: Replica, conn) -> None:
        with self._lock:
            if rep.healthy:
                self._conns.setdefault(rep.index, []).append(conn)
                return
        conn.close()

    def _forward(self, rep: Replica, path: str, body: bytes,
                 ctype: str):
        """One proxied POST.  A failure on a REUSED connection retries
        once on a fresh one (an idle kept-alive socket the replica
        timed out is stale, not a dead replica); a fresh-connection
        failure raises _ProxyError."""
        for attempt in (0, 1):
            conn, reused = self._conn_acquire(rep)
            if attempt and reused:
                # Second pass must be a real liveness probe.
                conn.close()
                conn, reused = http.client.HTTPConnection(
                    rep.host, rep.port, timeout=self._proxy_timeout_s
                ), False
            try:
                conn.request(
                    "POST", path, body=body,
                    headers={"Content-Type": ctype},
                )
                resp = conn.getresponse()
                data = resp.read()
            except (OSError, http.client.HTTPException) as e:
                conn.close()
                if reused:
                    continue  # stale pooled socket; try fresh
                raise _ProxyError(f"{type(e).__name__}: {e}") from e
            rctype = resp.getheader("Content-Type") or "text/plain"
            if resp.will_close or resp.status >= 400:
                conn.close()
            else:
                self._conn_release(rep, conn)
            return resp.status, data, rctype
        raise _ProxyError("unreachable")  # pragma: no cover

    # -- health ------------------------------------------------------------

    def _evict(self, rep: Replica, reason: str,
               quarantine: bool = False) -> None:
        with self._lock:
            if quarantine:
                rep.quarantined = True
            if not rep.healthy:
                return
            rep.healthy = False
            rep.fails = 0
            stale = self._conns.get(rep.index) or []
            self._conns[rep.index] = []
        for conn in stale:
            conn.close()
        self._c_evictions.add()
        log.warning(
            "replica %d (%s) EVICTED from routing: %s",
            rep.index, rep.address, reason,
        )

    def _readmit(self, rep: Replica) -> None:
        with self._lock:
            # A quarantined replica is ALIVE but serving unvetted
            # params (rejected canary, failed rollback): answering
            # /healthz must not put it back in rotation — only a later
            # successful promotion clears the quarantine.
            if rep.healthy or rep.quarantined:
                return
            rep.healthy = True
            rep.fails = 0
        self._c_readmissions.add()
        log.info("replica %d (%s) readmitted to routing",
                 rep.index, rep.address)

    def _probe_health(self, rep: Replica) -> bool:
        try:
            with urllib.request.urlopen(
                f"http://{rep.address}/healthz", timeout=1.0
            ) as resp:
                return resp.status == 200
        except (urllib.error.URLError, OSError):
            return False

    def _health_loop(self) -> None:
        while not self._stop.wait(self._health_secs):
            for rep in self._replicas:
                if self._stop.is_set():
                    return
                if rep.proc is not None and rep.proc.poll() is not None:
                    self._evict(
                        rep, f"process exited {rep.proc.poll()}"
                    )
                    continue
                if self._probe_health(rep):
                    with self._lock:
                        rep.fails = 0
                    self._readmit(rep)
                else:
                    with self._lock:
                        rep.fails += 1
                        dead = rep.healthy and rep.fails >= _FAIL_EVICT
                    if dead:
                        self._evict(
                            rep,
                            f"{rep.fails} consecutive /healthz "
                            "failures",
                        )

    # -- canary promotion ---------------------------------------------------

    def _admin(self, rep: Replica, route: str) -> dict:
        """POST an admin route on a replica; returns the JSON doc.
        Raises _ProxyError on connection failure and ValueError on a
        4xx/5xx (the replica refused — e.g. an unservable checkpoint)."""
        status, data, _ = self._forward(
            rep, route, b"", "application/octet-stream"
        )
        if status != 200:
            raise ValueError(
                f"replica {rep.index} {route} answered {status}: "
                f"{data[:200].decode(errors='replace')}"
            )
        return json.loads(data)

    def _canary_loop(self) -> None:
        poll = max(0.05, self.cfg.serve_poll_secs)
        while not self._stop.wait(poll):
            try:
                self._canary_check()
            except Exception as e:  # noqa: BLE001 - retry next poll
                log.warning(
                    "canary watcher: promotion attempt failed (%s); "
                    "will retry next poll", e,
                )

    def _canary_check(self) -> None:
        man = manifest.read_manifest(self.cfg.model_file)
        if man is None or man == self._seen:
            return
        with self._lock:
            healthy = [r for r in self._replicas if r.healthy]
        if len(healthy) < 2:
            # Promotion needs a canary AND a baseline; retry the next
            # poll (the manifest stays un-baselined, so an evicted
            # replica coming back resumes promotion).
            log.warning(
                "canary: new checkpoint published but only %d healthy "
                "replica(s); deferring promotion", len(healthy),
            )
            return
        canary, baseline = healthy[0], healthy[1]
        try:
            # keep_prev=1 opens the replica's rollback window (and
            # anchors it across a retried reload); the fleet-roll and
            # quarantine-recovery reloads below stay plain — they are
            # promoted immediately, so retaining a standby table would
            # only pin memory.
            step = int(self._admin(
                canary, "/reload?keep_prev=1"
            ).get("step", 0))
        except ValueError as e:
            # The replica REFUSED the checkpoint (dtype/shape/format
            # contradiction): permanent for this manifest — baseline
            # it like the single-process watcher does instead of
            # re-reading a multi-GB table every poll.
            log.warning(
                "canary reload refused (%s); keeping the current "
                "fleet, will pick up the next save", e,
            )
            self._seen = man
            return
        ok, detail = self._shadow_compare(canary, baseline, step)
        if ok:
            try:
                self._admin(canary, "/promote")
            except ValueError as e:  # pragma: no cover - defensive
                log.warning("canary promote failed: %s", e)
            promoted = 1
            for rep in healthy[1:]:
                try:
                    self._admin(rep, "/reload")
                    self._admin(rep, "/promote")
                    promoted += 1
                except (ValueError, _ProxyError) as e:
                    log.warning(
                        "rolling promotion: replica %d failed to "
                        "reload (%s) — it keeps serving the OLD "
                        "params until the next manifest", rep.index, e,
                    )
            self._c_promotions.add()
            self.step = step
            log.info(
                "canary promotion to step %d complete (%d/%d "
                "replicas; %s)", step, promoted, len(healthy), detail,
            )
            # A quarantined replica (rejected canary whose rollback
            # failed) can rejoin ONLY by landing on a vetted
            # checkpoint: reload it onto the step the fleet just
            # promoted, then clear the quarantine so the health loop
            # may readmit it.
            with self._lock:
                quarantined = [
                    r for r in self._replicas if r.quarantined
                ]
            for rep in quarantined:
                try:
                    self._admin(rep, "/reload")
                    self._admin(rep, "/promote")
                    with self._lock:
                        rep.quarantined = False
                    log.info(
                        "quarantined replica %d reloaded onto the "
                        "promoted step %d; eligible for readmission",
                        rep.index, step,
                    )
                except (ValueError, _ProxyError) as e:
                    log.warning(
                        "quarantined replica %d could not reload the "
                        "promoted checkpoint (%s); it stays out of "
                        "routing", rep.index, e,
                    )
        else:
            try:
                self._admin(canary, "/rollback")
            except (ValueError, _ProxyError) as e:
                log.warning(
                    "canary ROLLBACK FAILED on replica %d (%s) — "
                    "QUARANTINING it rather than serving an unvetted "
                    "table (a later successful promotion reloads and "
                    "readmits it)", canary.index, e,
                )
                self._evict(
                    canary,
                    "rollback failed after a rejected canary",
                    quarantine=True,
                )
            self._c_rollbacks.add()
            log.warning(
                "canary REJECTED at step %d: %s — rolled back; this "
                "manifest is baselined (republish to retry)",
                step, detail,
            )
        self._seen = man

    def _shadow_score(self, rep: Replica, path: str, body: bytes):
        """Replay one sampled request directly against a replica;
        returns its scores (list of float) or None on failure."""
        try:
            status, data, _ = self._forward(
                rep, path,
                body,
                "text/plain" if path == "/score"
                else "application/octet-stream",
            )
        except _ProxyError:
            return None
        if status != 200:
            return None
        try:
            if path == "/score":
                return [float(tok) for tok in data.split()]
            return [float(s) for s in wire.decode_bin_response(data)]
        except ValueError:
            return None

    def _gate_scale(self, scores) -> np.ndarray:
        """Scores on a ratio-stable scale for the drift gate.

        Logistic serving already answers sigmoid probabilities in
        (0, 1), where a ratio IS relative drift.  mse serving answers
        RAW scores, which routinely sit near (or straddle) zero — a
        raw-ratio gate there turns negligible absolute drift into
        huge ratios (or inf, or sign-flipped ratios), spuriously
        rejecting canaries.  Squashing raw scores through the same
        sigmoid gives a bounded positive scale that is monotone in
        the score, so real drift still moves every quantile.
        """
        arr = np.asarray(scores, np.float64)
        if self.cfg.loss_type != "logistic":
            arr = 1.0 / (1.0 + np.exp(-arr))
        return arr

    @staticmethod
    def _dist_stats(scores: np.ndarray) -> dict:
        return {
            "metric": "canary_shadow_scores",
            "score_n": int(len(scores)),
            "score_mean": float(np.mean(scores)),
            "score_std": float(np.std(scores)),
            "score_p10": float(np.percentile(scores, 10)),
            "score_p50": float(np.percentile(scores, 50)),
            "score_p90": float(np.percentile(scores, 90)),
        }

    def _shadow_compare(self, canary: Replica, baseline: Replica,
                        step: int):
        """Shadow-score the sampled traffic on the canary and a
        baseline replica and judge the two score distributions with
        ``tools/report.py --compare`` (exit 2 = drifted -> reject).
        Returns (ok, detail)."""
        sample = list(self._sample)
        if not sample:
            return True, ("no traffic sample collected; promoting "
                          "without a shadow compare")
        c_scores: list = []
        b_scores: list = []
        for path, body in sample:
            sc = self._shadow_score(canary, path, body)
            sb = self._shadow_score(baseline, path, body)
            if sc is None or sb is None or len(sc) != len(sb):
                continue
            c_scores.extend(sc)
            b_scores.extend(sb)
        if not c_scores:
            return True, ("shadow replay produced no comparable "
                          "scores; promoting")
        stats_b = self._dist_stats(self._gate_scale(b_scores))
        stats_c = self._dist_stats(self._gate_scale(c_scores))
        out_dir = os.path.join(
            os.path.abspath(self.cfg.model_file), "canary_compare",
            f"step_{step}",
        )
        os.makedirs(out_dir, exist_ok=True)
        path_b = os.path.join(out_dir, "baseline.json")
        path_c = os.path.join(out_dir, "canary.json")
        with open(path_b, "w") as f:
            json.dump(stats_b, f, indent=1)
        with open(path_c, "w") as f:
            json.dump(stats_c, f, indent=1)
        report = os.path.join(
            os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))
            )),
            "tools", "report.py",
        )
        if os.path.exists(report):
            proc = subprocess.run(
                [sys.executable, report, "--compare", path_b, path_c,
                 "--threshold", "default=0.05"],
                capture_output=True, timeout=60,
            )
            tail = proc.stdout.decode(errors="replace").strip(
            ).splitlines()[-1:] or [""]
            detail = (
                f"report.py --compare exit {proc.returncode} "
                f"({tail[0]}; artifacts in {out_dir})"
            )
            # Exit 0 = within threshold.  Exit 2 = drift.  Anything
            # else is a tooling failure — reject rather than promote
            # an unjudged table.
            return proc.returncode == 0, detail
        # Degraded in-process gate (report.py missing from this
        # install): same keys, same 5% ratio rule, flagged loudly.
        log.warning(
            "canary compare: %s not found; using the in-process "
            "ratio gate", report,
        )
        for key in ("score_mean", "score_p10", "score_p50",
                    "score_p90"):
            va, vb = stats_b[key], stats_c[key]
            if va == 0 and vb == 0:
                continue
            ratio = vb / va if va else float("inf")
            if not 0.95 <= ratio <= 1.05:
                return False, (
                    f"in-process gate: {key} ratio {ratio:.3f} "
                    f"(artifacts in {out_dir})"
                )
        return True, f"in-process gate passed (artifacts in {out_dir})"

    # -- record / metrics ----------------------------------------------------

    def _build(self, kind: str = "status") -> dict:
        now = time.time()
        wall = max(now - self._t0, 1e-9)
        snap = self._tel.snapshot()
        counters = snap.get("counters") or {}
        timers = snap.get("timers") or {}
        with self._lock:
            per = [
                {
                    "index": r.index, "port": r.port, "pid": r.pid,
                    "healthy": r.healthy,
                    "quarantined": r.quarantined,
                    "inflight": r.inflight, "routed": r.routed,
                }
                for r in self._replicas
            ]
        requests = int(counters.get("serve.router_requests", 0))
        shed = int(counters.get("serve.shed", 0))
        block = {
            "requests": requests,
            "shed": shed,
            "shed_frac": round(shed / requests, 6) if requests else 0.0,
            "qps": round(requests / wall, 2),
            "inflight": sum(p["inflight"] for p in per),
            "replicas": len(per),
            "replicas_healthy": sum(1 for p in per if p["healthy"]),
            "evictions": int(counters.get("serve.evictions", 0)),
            "readmissions": int(
                counters.get("serve.readmissions", 0)
            ),
            "retries": int(counters.get("serve.retries", 0)),
            "canary_promotions": int(
                counters.get("serve.canary_promotions", 0)
            ),
            "canary_rollbacks": int(
                counters.get("serve.canary_rollbacks", 0)
            ),
            "per_replica": per,  # /status detail; non-numeric, so the
        }                        # Prometheus rendering skips it
        proxy = timers.get("serve.proxy") or {}
        for key in ("mean_ms", "p50_ms", "p95_ms", "p99_ms", "max_ms"):
            if key in proxy:
                block[key] = proxy[key]
        return {
            "record": kind,
            "time": now,
            "elapsed": round(wall, 3),
            "step": self.step,
            "serve": block,
            "stages": snap,
        }

    def _render_metrics(self) -> str:
        record = self._build("status")
        per = record["serve"]["per_replica"]
        lines = [render_prometheus(record).rstrip("\n")]
        lines.append("# TYPE tffm_serve_replica_healthy gauge")
        for p in per:
            lines.append(
                f'tffm_serve_replica_healthy{{replica="{p["index"]}",'
                f'port="{p["port"]}"}} {1 if p["healthy"] else 0}'
            )
        lines.append("# TYPE tffm_serve_replica_inflight gauge")
        for p in per:
            lines.append(
                f'tffm_serve_replica_inflight{{replica='
                f'"{p["index"]}"}} {p["inflight"]}'
            )
        lines.append("# TYPE tffm_serve_replica_routed_total counter")
        for p in per:
            lines.append(
                f'tffm_serve_replica_routed_total{{replica='
                f'"{p["index"]}"}} {p["routed"]}'
            )
        return "\n".join(lines) + "\n"

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        self._httpd.shutdown()
        self._thread.join()
        self._httpd.server_close()
        self._health_thread.join()
        if self._canary_thread is not None:
            self._canary_thread.join()
        with self._lock:
            pools = list(self._conns.values())
            self._conns = {}
        for pool in pools:
            for conn in pool:
                conn.close()


class FleetHandle:
    """One running router + replica fleet; ``close()`` tears it down in
    order (router stops routing, replicas terminate, final record
    written)."""

    def __init__(self, cfg, manager, router, telemetry, writer,
                 heartbeat):
        self.cfg = cfg
        self.manager = manager
        self.router = router
        self.replicas = router._replicas
        self.telemetry = telemetry
        self.port = router.port
        self._writer = writer
        self._heartbeat = heartbeat
        self._closed = False

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._heartbeat is not None:
            self._heartbeat.close()
        self.router.close()
        if self.manager is not None:
            self.manager.close()
        if self._writer is not None:
            try:
                self._writer.write(self.router._build("final"))
            except Exception as e:  # noqa: BLE001 - teardown best-effort
                log.warning("router final record write failed: %s", e)
            self._writer.close()


def start_fleet(cfg: FmConfig, cfg_path: str,
                overrides: Optional[dict] = None,
                port: Optional[int] = None) -> FleetHandle:
    """Spawn the replica fleet and mount the router over it.

    ``port`` overrides ``cfg.serve_port`` (tests pass 0).  The manifest
    baseline is captured BEFORE the replicas spawn, so a checkpoint
    published during their warmup still looks new to the canary
    watcher's first poll.
    """
    writer = (
        obs.JsonlWriter(cfg.metrics_file) if cfg.metrics_file else None
    )
    telemetry = obs.Telemetry(enabled=cfg.telemetry)
    manifest_seen = manifest.read_manifest(cfg.model_file)
    manager = None
    router = None
    heartbeat = None
    try:
        manager = ReplicaManager(cfg, cfg_path, overrides=overrides)
        router = ServeRouter(
            cfg.serve_port if port is None else port,
            manager.replicas, cfg, telemetry=telemetry, writer=writer,
            host=cfg.serve_host, manifest_seen=manifest_seen,
        )
        if writer is not None:
            writer.write({
                "record": "run_header",
                "mode": "serve_router",
                "time": time.time(),
                "model_file": cfg.model_file,
                "resume_step": router.step,
                "batch_size": cfg.batch_size,
                "telemetry": cfg.telemetry,
                "heartbeat_secs": cfg.heartbeat_secs,
                "serve_replicas": cfg.serve_replicas,
                "serve_shed_deadline_ms": cfg.serve_shed_deadline_ms,
                "serve_canary": cfg.serve_canary,
                "serve_transport": cfg.serve_transport,
                "serve_poll_secs": cfg.serve_poll_secs,
                "replica_ports": [r.port for r in manager.replicas],
            })
        if cfg.heartbeat_secs > 0:
            heartbeat = obs.Heartbeat(
                cfg.heartbeat_secs,
                lambda: router._build("heartbeat"),
                writer=writer,
            )
    except BaseException:
        # A failed mount must not leak replica processes or threads.
        if router is not None:
            router.close()
        if manager is not None:
            manager.close()
        if writer is not None:
            writer.close()
        raise
    log.info(
        "router listening on %s:%d over %d replicas (POST /score, "
        "/score_bin; GET /metrics, /status, /healthz)",
        cfg.serve_host, router.port, len(manager.replicas),
    )
    return FleetHandle(cfg, manager, router, telemetry, writer,
                       heartbeat)


def serve_fleet(cfg: FmConfig, cfg_path: str,
                overrides: Optional[dict] = None) -> int:
    """CLI entry for ``run_tffm.py serve <cfg> --replicas N``: route
    until interrupted.  SIGTERM and SIGINT both tear the fleet down —
    the replica subprocesses must never outlive their router."""
    handle = start_fleet(cfg, cfg_path, overrides=overrides)
    print(
        f"routing on {cfg.serve_host}:{handle.port} across "
        f"{len(handle.replicas)} replica(s)", flush=True,
    )

    def _sigterm(signum, frame):  # pragma: no cover - signal path
        raise KeyboardInterrupt

    prev = signal.signal(signal.SIGTERM, _sigterm)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        log.info("interrupted; shutting down the fleet")
    finally:
        handle.close()
        signal.signal(signal.SIGTERM, prev)
    return 0
