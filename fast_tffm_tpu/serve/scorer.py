"""Compiled fixed-shape scorers: the serving path's device half.

Online traffic arrives at arbitrary sizes; a jit that traces per
request shape would recompile constantly (a multi-second stall per new
shape) and XLA executables only exist at fixed shapes anyway.  The
scorers here pin a small LADDER of microbatch shapes (``{64, 256,
1024}`` examples x ``max_features`` by default, ``serve_batch_sizes``)
and pad every request/chunk up to the smallest rung that holds it:

- every rung is precompiled at startup through an AOT
  ``.lower().compile()`` cache (:meth:`warmup`), so steady-state
  serving NEVER compiles — the zero-compile contract the serving tests
  pin (``steady_compiles``);
- input buffers are donated (``donate_argnums``): XLA may reuse the
  microbatch's device memory for the result, and the host side fills
  recycled per-rung staging buffers (the prefetcher's staging-pool
  discipline) instead of allocating per dispatch;
- parameters are an ARGUMENT of the compiled function, not a constant —
  so a warm checkpoint hot-swap is one reference swap between
  dispatches (:meth:`swap`), zero recompiles, and a dispatch always
  scores against exactly one table (old or new, never torn).

Two variants share the plumbing: :class:`FixedShapeScorer` scores a
dense device-resident table (the ordinary checkpoint format), and
:class:`OverlayScorer` scores straight from a huge-V ``tiered.npz``
sparse-overlay checkpoint — per chunk it remaps the batch's unique ids
to a compact bucket-padded table gathered from the host cold store
(the same compact-table trick the tiered trainer's validation path
uses), so a V >= 2^28 model serves without ever materializing [V, D].

Compile accounting mirrors the trainer's sentinel: every compile is
timed into a ``serve.compile`` timer and written as a ``record:
compile`` JSONL entry (``where: serve``); a compile at a shape OUTSIDE
the ladder bumps ``serve.recompiles_unexpected`` and warns — on the
serving path an unexpected compile is a multi-second latency cliff.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
import warnings
from concurrent.futures import ThreadPoolExecutor
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from fast_tffm_tpu import obs
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.models import fm
from fast_tffm_tpu.ops import autotune as autotune_lib
from fast_tffm_tpu.ops import quant
from fast_tffm_tpu.parallel import mesh as mesh_lib
from fast_tffm_tpu.train import checkpoint
from fast_tffm_tpu.train import tiered as tiered_lib

log = logging.getLogger(__name__)

__all__ = [
    "FixedShapeScorer", "OverlayScorer", "load_model", "make_scorer",
]


def _round_up(n: int, mult: int) -> int:
    return -(-n // mult) * mult


@contextlib.contextmanager
def _quiet_donation():
    """Silence the per-compile "donated buffers were not usable"
    UserWarning: donation is a best-effort device-memory optimization
    (it pays off where input/output buffers can alias, e.g. TPU); on
    backends where it can't, one warning per ladder rung at startup is
    pure noise."""
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message=".*donated buffers were not usable.*"
        )
        yield


class _LadderScorer:
    """Shared rung/pool/compile plumbing of the two scorer variants.

    Thread contract: :meth:`score` / :meth:`score_rung` serialize on one
    lock (the batcher dispatches from a single thread anyway; the lock
    makes direct callers safe too).  :meth:`swap` may run on any thread:
    it replaces the model REFERENCE under ``_swap_lock``, and a dispatch
    grabs that reference once and uses it for the whole microbatch — so
    every dispatch scores against exactly one model (old or new, never
    torn) and a swap never waits on traffic.
    """

    def __init__(self, cfg: FmConfig, mesh=None, telemetry=None,
                 writer=None, extra_rungs=()):
        self.cfg = cfg
        self.mesh = mesh if mesh is not None else mesh_lib.make_mesh(cfg)
        data_n = int(self.mesh.shape[mesh_lib.DATA_AXIS])
        rungs = sorted({
            _round_up(int(b), data_n)
            for b in tuple(cfg.serve_ladder) + tuple(extra_rungs)
            if int(b) > 0
        })
        self.ladder = tuple(rungs)
        self.max_rung = self.ladder[-1]
        self._ladder_set = set(self.ladder)
        tel = telemetry if telemetry is not None else obs.NULL
        self._tel = tel
        self._t_compile = tel.timer("serve.compile")
        self._t_dispatch = tel.timer("serve.dispatch")
        self._c_unexpected = tel.counter("serve.recompiles_unexpected")
        self._c_swaps = tel.counter("serve.swaps")
        self._writer = writer
        self._lock = threading.Lock()  # serializes dispatch + pools
        self._swap_lock = threading.Lock()
        # Previous model retained by a keep_prev swap (the canary
        # protocol's rollback window): (model_ref, step) or None.
        # Holding it costs one standby table's memory, so it exists
        # only between a keep_prev swap and the promote()/rollback()
        # decision.
        self._prev = None
        self._cache: dict = {}
        self._pools: dict = {}  # rung -> (ids, vals, fields) host buffers
        self._aot_broken = False
        self._warmed = False
        # Whether EXPECTED compiles may legitimately happen after
        # warmup: False for the dense scorer (warmup compiles the whole
        # ladder, so any later compile is the latency-cliff signal);
        # True for the overlay scorer (compact-table buckets compile
        # lazily, O(log) of them, by design).
        self._lazy_expected_ok = False
        self.steady_compiles = 0  # post-warmup latency-cliff compiles
        self.compiles = 0
        self.step = 0  # checkpoint step currently served (0 = in-memory)
        F = cfg.max_features
        sh = mesh_lib.batch_sharding(self.mesh)
        self._arg_sh = (sh["ids"], sh["vals"], sh["fields"])
        self._arg_dtypes = (np.int32, np.float32, np.int32)
        self._n_args = 3 if cfg.field_num else 2
        self._feat = F
        # Compile accounting may run from several warmup threads (the
        # rung ladder compiles concurrently); a lock keeps the counter
        # increments, steady accounting, and record writes coherent.
        self._compile_lock = threading.Lock()
        self.warmup_wall_s = 0.0  # wall clock of the last warmup()
        self.warmup_compile_s = 0.0  # summed compile time inside warmup
        # Kernel autotune (ops/autotune.py): with interaction_impl set,
        # resolve the serve-path interaction impl at the max rung's
        # shape (auto measures + parity-gates against reference; pins
        # and the single-candidate CPU case skip measurement).  The
        # resolved internal name routes the rung score math through
        # ops.interaction._forward; None keeps the historical
        # closed-form path bit-identical (reference IS that path).
        self._impl = None
        self.kernel_impl = "reference"
        if cfg.interaction_impl:
            d = autotune_lib.resolve(
                cfg, context="serve", batch=self.max_rung, writer=writer,
            )
            self.kernel_impl = d.impl
            self._impl = None if d.interaction == "jnp" else d.interaction

    # -- rung / pool helpers -------------------------------------------

    def rung_for(self, n: int) -> int:
        """Smallest ladder rung holding ``n`` examples (the max rung for
        anything larger — callers chunk)."""
        for b in self.ladder:
            if n <= b:
                return b
        return self.max_rung

    def slots_for(self, n: int) -> int:
        """Total padded slots :meth:`score` dispatches for ``n``
        examples — the chunk policy's accounting twin, kept HERE so
        fill-fraction bookkeeping can never drift from the actual
        chunking."""
        slots = 0
        pos = 0
        while pos < n:
            c = min(n - pos, self.max_rung)
            slots += self.rung_for(c)
            pos += c
        return slots

    def _pool(self, b: int):
        bufs = self._pools.get(b)
        if bufs is None:
            bufs = tuple(
                np.zeros((b, self._feat), dt) for dt in self._arg_dtypes
            )
            self._pools[b] = bufs
        return bufs

    def _finish(self, s):
        """Score post-processing shared by both variants: probabilities
        for logistic loss, raw scores for mse (predict's contract)."""
        if self.cfg.loss_type == "logistic":
            s = jax.nn.sigmoid(s)
        return s

    def _aot_fail(self, e: BaseException):
        """Permanent fallback on AOT API drift: dispatch through the
        plain jit (identical math; compiles become invisible to the
        zero-compile accounting, so say so loudly)."""
        self._aot_broken = True
        log.warning(
            "serve AOT compile path unavailable (%s: %s); falling back "
            "to plain jit dispatch (compiles become invisible to the "
            "zero-compile accounting)", type(e).__name__, e,
        )
        return self._jit

    # -- compile accounting --------------------------------------------

    def _account_compile(self, wall: float, key, expected: bool) -> None:
        with self._compile_lock:
            self._t_compile.observe(wall)
            self.compiles += 1
            if not self._warmed:
                self.warmup_compile_s += wall
            if self._warmed and not (expected and self._lazy_expected_ok):
                self.steady_compiles += 1
        if not expected:
            self._c_unexpected.add()
            log.warning(
                "UNEXPECTED serve-path compile (%s, %.2fs): the shape "
                "is outside the configured serve_batch_sizes ladder — "
                "a multi-second latency cliff on the hot path",
                key, wall,
            )
        if self._writer is not None:
            try:
                self._writer.write({
                    "record": "compile",
                    "where": "serve",
                    "time": time.time(),
                    "shape": list(key) if isinstance(key, tuple) else key,
                    "compile_s": round(wall, 4),
                    "expected": bool(expected),
                })
            except Exception as e:  # noqa: BLE001 - never kill a compile
                log.warning("serve compile record write failed: %s", e)

    def warmup(self) -> int:
        """Precompile every ladder rung; returns the compile count.
        After this returns, a correctly-configured server never
        compiles again (``steady_compiles`` stays 0).

        Rungs compile CONCURRENTLY: each rung is an independent
        ``.lower().compile()`` at a distinct cache key and XLA releases
        the GIL while compiling, so a thread per rung overlaps what
        used to be a serial multi-second ladder walk.  The saving is
        recorded (``warmup_compile_s`` summed vs ``warmup_wall_s``
        observed) — with a populated persistent compile cache both
        collapse to near zero and the warm-spawn zero-fresh-lowers
        contract is checkable.
        """
        t0 = time.perf_counter()
        with self._lock:
            if len(self.ladder) > 1 and not self._aot_broken:
                with ThreadPoolExecutor(
                    max_workers=min(len(self.ladder), 8),
                    thread_name_prefix="tffm-warmup",
                ) as ex:
                    # list() re-raises the first rung failure, matching
                    # the serial path's error contract.
                    list(ex.map(self._warm_rung, self.ladder))
            else:
                for b in self.ladder:
                    self._warm_rung(b)
        self.warmup_wall_s = time.perf_counter() - t0
        self._warmed = True
        self.steady_compiles = 0
        if self.compiles and self.warmup_compile_s > self.warmup_wall_s:
            log.info(
                "concurrent ladder warmup: %.2fs of compiles in %.2fs "
                "wall (%.2fs saved)",
                self.warmup_compile_s, self.warmup_wall_s,
                self.warmup_compile_s - self.warmup_wall_s,
            )
        return self.compiles

    # -- scoring -------------------------------------------------------

    def score(self, ids: np.ndarray, vals: np.ndarray,
              fields: Optional[np.ndarray] = None) -> np.ndarray:
        """Scores for ``n`` examples (``[n, max_features]`` arrays), any
        ``n``: chunks at the max rung, pads the tail chunk up to its
        rung with zero rows (``vals == 0`` rows are mathematically inert
        and their outputs are discarded)."""
        n = len(ids)
        out = np.empty((n,), np.float32)
        pos = 0
        with self._lock:
            while pos < n:
                c = min(n - pos, self.max_rung)
                b = self.rung_for(c)
                bi, bv, bf = self._pool(b)
                bi[:c] = ids[pos:pos + c]
                bv[:c] = vals[pos:pos + c]
                if c < b:
                    bi[c:] = 0
                    bv[c:] = 0.0
                if self._n_args == 3:
                    if fields is not None:
                        bf[:c] = fields[pos:pos + c]
                    else:
                        bf[:c] = 0
                    if c < b:
                        bf[c:] = 0
                scores = self._dispatch_rung(bi, bv, bf, b)
                out[pos:pos + c] = scores[:c]
                pos += c
        return out

    def score_rung(self, ids: np.ndarray, vals: np.ndarray,
                   fields: Optional[np.ndarray], b: int) -> np.ndarray:
        """One dispatch of exactly-rung-shaped arrays (the batcher's
        entry: it fills the pooled buffers itself)."""
        with self._lock:
            if fields is None:
                fields = self._pool(b)[2]
                if self._n_args == 3:
                    # The pool buffer is shared across dispatches: a
                    # fields-less group must not score against field
                    # values a previous group left behind.
                    fields[:] = 0
            return self._dispatch_rung(ids, vals, fields, b)

    # -- canary promote / rollback -------------------------------------

    def promote(self) -> None:
        """Drop the previous model a ``keep_prev`` swap retained: the
        new params are now the fleet's truth and the rollback window is
        closed (frees the standby table's memory)."""
        with self._swap_lock:
            self._prev = None

    def rollback(self) -> bool:
        """Restore the model a ``keep_prev`` swap replaced (the canary
        failed its shadow compare).  One reference swap between
        dispatches, same never-torn contract as :meth:`swap`.  Returns
        False when there is nothing to roll back to."""
        with self._swap_lock:
            if self._prev is None:
                return False
            model, step = self._prev
            self._prev = None
            self._set_model(model)
            self.step = int(step)
        self._c_swaps.add()
        log.info("serving params rolled back to step %d", step)
        return True

    # -- subclass hooks ------------------------------------------------

    def _set_model(self, model) -> None:
        """Install a model reference (rollback path); caller holds
        ``_swap_lock``."""
        raise NotImplementedError

    def _warm_rung(self, b: int) -> None:
        raise NotImplementedError

    def _dispatch_rung(self, ids, vals, fields, b: int) -> np.ndarray:
        raise NotImplementedError


class FixedShapeScorer(_LadderScorer):
    """Dense-table scorer: params device-resident, hot-swappable.

    ``params`` may be a host-numpy or device :class:`fm.FmParams`
    (fp32), or — for a ``quant.npz`` checkpoint — a ``(w0,
    quant.QuantTable)`` pair; it is placed with the mesh's param
    sharding either way.

    ``serve_table_dtype`` picks the DEVICE-RESIDENT storage format:

    - ``fp32`` — the historical path, bit-identical scores;
    - ``bf16`` — the table device-residents as bfloat16 (half the
      bytes); the compiled rungs gather compact rows and the existing
      f32 upcast in the score math widens them in-register;
    - ``int8`` — codes + per-``quant_chunk``-rows fp32 scales
      (~quarter the bytes); the compiled rungs run
      ``fm.fm_scores_dequant`` (gather codes + scale chunk, widen
      in-register, score).

    Either way the rung shapes are unchanged, so the AOT ladder /
    zero-steady-compile contract and the hot-swap protocol carry over
    verbatim: an fp32 checkpoint swap quantizes host-side into standby
    buffers off-traffic.  ``serve.table_bytes`` gauges the resident
    table footprint and ``serve.quant_error_max`` the max
    |score_fp32 − score_quant| on a deterministic probe batch measured
    at placement time (0 for fp32).
    """

    def __init__(self, cfg: FmConfig, params, mesh=None,
                 telemetry=None, writer=None, extra_rungs=(), step=0):
        super().__init__(cfg, mesh=mesh, telemetry=telemetry,
                         writer=writer, extra_rungs=extra_rungs)
        self.step = int(step)
        self.table_dtype = quant.validate_dtype(
            cfg.serve_table_dtype, "serve_table_dtype"
        )
        self._chunk = cfg.quant_chunk
        self._param_sh = mesh_lib.param_sharding(self.mesh)
        self._g_table_bytes = self._tel.gauge("serve.table_bytes")
        self._g_quant_err = self._tel.gauge("serve.quant_error_max")
        self._params = self._place(params)
        impl = self._impl  # autotune-resolved interaction routing
        if self.table_dtype == "int8":
            chunk = self._chunk
            if cfg.field_num:
                def score_fn(params, ids, vals, fields):
                    return self._finish(fm.fm_scores_dequant(
                        params.w0, params.codes, params.scales, chunk,
                        ids, vals, fields,
                        factor_num=cfg.factor_num,
                        field_num=cfg.field_num,
                    ))
            else:
                def score_fn(params, ids, vals):
                    return self._finish(fm.fm_scores_dequant(
                        params.w0, params.codes, params.scales, chunk,
                        ids, vals, None,
                        factor_num=cfg.factor_num, field_num=0,
                        impl=impl,
                    ))
            param_sh_tree = quant.QuantParams(
                w0=self._param_sh.w0,
                codes=self._param_sh.table,
                # The scale vector is tiny (V / chunk floats) and 1-D:
                # replicate it rather than invent a 1-axis sharding.
                scales=NamedSharding(self.mesh, P()),
            )
        else:
            # fp32 and bf16 share the FmParams score path: the gather
            # reads whatever dtype the table stores and the score
            # math's astype widens it in-register (ops/interaction.py).
            if cfg.field_num:
                def score_fn(params, ids, vals, fields):
                    return self._finish(fm.fm_scores(
                        params, ids, vals, fields,
                        factor_num=cfg.factor_num,
                        field_num=cfg.field_num,
                    ))
            else:
                def score_fn(params, ids, vals):
                    return self._finish(fm.fm_scores(
                        params, ids, vals, None,
                        factor_num=cfg.factor_num, field_num=0,
                        impl=impl,
                    ))
            param_sh_tree = self._param_sh
        self._jit = jax.jit(
            score_fn,
            in_shardings=(
                (param_sh_tree,) + self._arg_sh[:self._n_args]
            ),
            donate_argnums=tuple(range(1, 1 + self._n_args)),
        )

    # -- placement (construction + hot-swap staging) -------------------

    def _probe_quant_error(self, w0, table_f32: np.ndarray,
                           qt: "quant.QuantTable") -> float:
        """max |served_fp32 − served_quant| on a deterministic probe
        batch — host-side eager math (no ladder compile, so warmup
        accounting stays exact), gathering ONLY the probe rows from
        either side (dequantizing the full [V, D] table to read a few
        hundred rows would be a multi-GB allocation per hot-swap at
        real vocabularies); the REAL compiled-path tolerance is pinned
        by tests/test_quant.py."""
        cfg = self.cfg
        rng = np.random.default_rng(0xC0FFEE)
        n = min(256, cfg.vocabulary_size)
        ids = rng.integers(
            0, cfg.vocabulary_size, (n, cfg.max_features)
        ).astype(np.int64)
        vals = rng.uniform(0.1, 1.0, ids.shape).astype(np.float32)
        fields = (
            rng.integers(0, cfg.field_num, ids.shape).astype(np.int32)
            if cfg.field_num else None
        )
        w0j = jnp.asarray(w0, jnp.float32)

        def score(rows):
            return self._finish(fm.scores_from_rows(
                w0j, jnp.asarray(rows), jnp.asarray(vals),
                None if fields is None else jnp.asarray(fields),
                factor_num=cfg.factor_num, field_num=cfg.field_num,
            ))

        return float(jnp.max(jnp.abs(
            score(table_f32[ids]) - score(quant.dequantize_rows(qt, ids))
        )))

    def _place(self, params):
        dtype = self.table_dtype
        if isinstance(params, fm.FmParams):
            qt = None
        else:
            try:
                w0_in, qt = params
            except (TypeError, ValueError):
                raise ValueError(
                    "FixedShapeScorer params must be fm.FmParams or a "
                    f"(w0, QuantTable) pair, got {type(params).__name__}"
                ) from None
        if dtype == "fp32":
            if qt is not None:
                raise ValueError(
                    "a quantized (quant.npz) table cannot serve with "
                    "serve_table_dtype=fp32 — set serve_table_dtype to "
                    f"the checkpoint's dtype ({qt.dtype}) or convert "
                    "it back (python -m tools.convert_checkpoint "
                    "<dir> --to fp32)"
                )
            placed = fm.FmParams(
                w0=jax.device_put(
                    jnp.asarray(params.w0, jnp.float32),
                    self._param_sh.w0,
                ),
                table=jax.device_put(
                    jnp.asarray(params.table, jnp.float32),
                    self._param_sh.table,
                ),
            )
            table_bytes = (
                self.cfg.vocabulary_size * self.cfg.embedding_dim * 4
            )
            err = 0.0  # fp32 serving IS the reference
        else:
            if qt is None:
                # Quantize an fp32 checkpoint host-side, off-traffic
                # (construction or hot-swap staging).
                w0_in = np.float32(np.asarray(params.w0))
                table = np.asarray(params.table, np.float32)
                qt = quant.quantize_table(table, dtype, self._chunk)
                err = self._probe_quant_error(w0_in, table, qt)
            else:
                if qt.dtype != dtype:
                    raise ValueError(
                        f"quantized checkpoint is {qt.dtype} but "
                        f"serve_table_dtype={dtype}; they must match "
                        "(or convert the checkpoint)"
                    )
                if dtype == "int8" and int(qt.chunk) != int(self._chunk):
                    raise ValueError(
                        f"quantized checkpoint uses quant_chunk="
                        f"{qt.chunk} but the server is configured "
                        f"with quant_chunk={self._chunk}; they must "
                        "match (scale indexing is chunk-derived)"
                    )
                # No fp32 reference in hand (the checkpoint IS the
                # quantized table): -1 marks the gauge UNKNOWN rather
                # than leaving a previous placement's number (or a
                # lying 0) standing — documented in the metric schema.
                err = -1.0
            w0d = jax.device_put(
                jnp.asarray(w0_in, jnp.float32), self._param_sh.w0
            )
            if dtype == "bf16":
                placed = fm.FmParams(
                    w0=w0d,
                    table=jax.device_put(
                        jnp.asarray(qt.codes, jnp.bfloat16),
                        self._param_sh.table,
                    ),
                )
            else:
                placed = quant.QuantParams(
                    w0=w0d,
                    codes=jax.device_put(
                        jnp.asarray(qt.codes), self._param_sh.table
                    ),
                    scales=jax.device_put(
                        jnp.asarray(qt.scales, jnp.float32),
                        NamedSharding(self.mesh, P()),
                    ),
                )
            table_bytes = qt.nbytes
        jax.block_until_ready(placed)
        self._g_table_bytes.set(int(table_bytes))
        self._g_quant_err.set(float(err))
        return placed

    def swap(self, params, step: int = 0, keep_prev: bool = False
             ) -> None:
        """Warm hot-swap: stage the new params into standby device
        buffers (off the dispatch lock — traffic keeps scoring the old
        table; a quantized scorer quantizes the incoming fp32 table
        here too), then swap the reference atomically between
        dispatches.  Shapes are unchanged, so the compiled rungs serve
        on with zero recompiles; no request ever sees a torn table.
        ``keep_prev`` retains the replaced model for a later
        :meth:`rollback` (the canary window) at the cost of one standby
        table's memory until :meth:`promote`."""
        placed = self._place(params)  # standby buffers, fully resident
        with self._swap_lock:
            if keep_prev:
                # ANCHOR, don't clobber: if a rollback window is
                # already open (a canary check that died between its
                # reload and its verdict retries the reload), the
                # restorable params must stay the last VETTED ones —
                # overwriting them with the current (unvetted) model
                # would make a later rollback silently a no-op.
                if self._prev is None:
                    self._prev = (self._params, self.step)
            else:
                self._prev = None
            self._params = placed
            self.step = int(step)
        self._c_swaps.add()
        log.info("serving params hot-swapped to step %d", step)

    def _set_model(self, model) -> None:
        self._params = model

    def _compiled(self, b: int):
        fn = self._cache.get(b)
        if fn is not None:
            return fn
        if self._aot_broken:
            return self._jit
        structs = tuple(
            jax.ShapeDtypeStruct((b, self._feat), dt)
            for dt in self._arg_dtypes[:self._n_args]
        )
        p_struct = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), self._params
        )
        t0 = time.perf_counter()
        try:
            with _quiet_donation():
                fn = self._jit.lower(p_struct, *structs).compile()
        except Exception as e:  # pragma: no cover - jax API drift
            return self._aot_fail(e)
        self._account_compile(
            time.perf_counter() - t0, b, expected=b in self._ladder_set
        )
        self._cache[b] = fn
        return fn

    def _warm_rung(self, b: int) -> None:
        self._compiled(b)

    def _dispatch_rung(self, ids, vals, fields, b: int) -> np.ndarray:
        with self._t_dispatch.time():
            fn = self._compiled(b)
            with self._swap_lock:
                params = self._params
            if self._n_args == 3:
                out = fn(params, ids, vals, fields)
            else:
                out = fn(params, ids, vals)
            # The blocking host read is part of the dispatch: the score
            # goes back to a client, so D2H latency is request latency.
            return np.asarray(out)


class OverlayScorer(_LadderScorer):
    """Huge-V scorer over a ``tiered.npz`` sparse-overlay checkpoint.

    Per chunk: the chunk's unique logical ids gather their current rows
    from the host cold store (written overlay + deterministic init),
    the compact table bucket-pads to O(log) distinct row counts
    (``tiered._bucket``), and ids remap to local indices — identical
    math to a full-table gather without ever materializing [V, D].
    Compile cache keys on (rung, bucketed rows): both dimensions come
    from small ladders, so the executable set stays tiny and every
    compile at a bucketed shape is expected.
    """

    def __init__(self, cfg: FmConfig, w0: float, store, mesh=None,
                 telemetry=None, writer=None, extra_rungs=(), step=0):
        super().__init__(cfg, mesh=mesh, telemetry=telemetry,
                         writer=writer, extra_rungs=extra_rungs)
        self.step = int(step)
        self._lazy_expected_ok = True  # bucket shapes compile lazily
        self._rep = NamedSharding(self.mesh, P())
        self._model = (np.float32(w0), store)
        dim = cfg.embedding_dim
        if cfg.field_num:
            def score_fn(w0, table, ids, vals, fields):
                return self._finish(fm.fm_scores(
                    fm.FmParams(w0=w0, table=table), ids, vals, fields,
                    factor_num=cfg.factor_num, field_num=cfg.field_num,
                ))
        else:
            def score_fn(w0, table, ids, vals):
                return self._finish(fm.fm_scores(
                    fm.FmParams(w0=w0, table=table), ids, vals, None,
                    factor_num=cfg.factor_num, field_num=0,
                ))
        # The compact table is replicated: it is per-chunk data, not the
        # sharded logical table (which never materializes).
        self._jit = jax.jit(
            score_fn,
            in_shardings=(
                (self._rep, self._rep)
                + tuple(
                    NamedSharding(self.mesh, P(mesh_lib.DATA_AXIS, None))
                    for _ in range(self._n_args)
                )
            ),
            donate_argnums=tuple(range(2, 2 + self._n_args)),
        )
        self._dim = dim

    def swap(self, w0: float, store, step: int = 0,
             keep_prev: bool = False) -> None:
        """Hot-swap to a freshly restored overlay (new cold store +
        scalars).  One reference swap between dispatches — a chunk
        gathers its compact table from exactly one store.
        ``keep_prev`` retains the replaced overlay for
        :meth:`rollback` until :meth:`promote`."""
        with self._swap_lock:
            if keep_prev:
                # Same anchoring rule as the dense scorer: an open
                # rollback window keeps pointing at the last vetted
                # overlay across repeated keep_prev swaps.
                if self._prev is None:
                    self._prev = (self._model, self.step)
            else:
                self._prev = None
            self._model = (np.float32(w0), store)
            self.step = int(step)
        self._c_swaps.add()
        log.info("serving overlay hot-swapped to step %d", step)

    def _set_model(self, model) -> None:
        self._model = model

    def _compiled(self, b: int, rows: int):
        key = (b, rows)
        fn = self._cache.get(key)
        if fn is not None:
            return fn
        if self._aot_broken:
            return self._jit
        structs = (
            jax.ShapeDtypeStruct((), np.float32),
            jax.ShapeDtypeStruct((rows, self._dim), np.float32),
        ) + tuple(
            jax.ShapeDtypeStruct((b, self._feat), dt)
            for dt in self._arg_dtypes[:self._n_args]
        )
        t0 = time.perf_counter()
        try:
            with _quiet_donation():
                fn = self._jit.lower(*structs).compile()
        except Exception as e:  # pragma: no cover - jax API drift
            return self._aot_fail(e)
        # Bucketed compact-table shapes are all expected: the row
        # ladder is log-sized by construction, the rung ladder by
        # config.  Off-ladder RUNGS still flag.
        expected = b in self._ladder_set and rows == tiered_lib._bucket(
            max(1, rows), lo=8
        )
        self._account_compile(time.perf_counter() - t0, key, expected)
        self._cache[key] = fn
        return fn

    def _warm_rung(self, b: int) -> None:
        # Warm the smallest compact-table bucket per rung; larger
        # buckets compile lazily (still expected — log-many of them).
        self._compiled(b, tiered_lib._bucket(1))

    def _dispatch_rung(self, ids, vals, fields, b: int) -> np.ndarray:
        with self._t_dispatch.time():
            with self._swap_lock:
                w0, store = self._model
            vocab = self.cfg.vocabulary_size
            flat = ids.reshape(-1).astype(np.int64, copy=False)
            safe = np.where((flat >= 0) & (flat < vocab), flat, 0)
            u, inv = np.unique(safe, return_inverse=True)
            rows = tiered_lib._bucket(max(1, len(u)))
            mini = np.zeros((rows, self._dim), np.float32)
            mini[:len(u)] = store.gather(u)
            local_ids = inv.astype(np.int32).reshape(ids.shape)
            fn = self._compiled(b, rows)
            if self._n_args == 3:
                out = fn(w0, mini, local_ids, vals, fields)
            else:
                out = fn(w0, mini, local_ids, vals)
            return np.asarray(out)


# ----------------------------------------------------------------------
# checkpoint loading (construction + the hot-swap watcher's reload)
# ----------------------------------------------------------------------


def load_model(cfg: FmConfig, mesh=None):
    """Load the servable model from ``cfg.model_file``.

    Returns ``("dense", step, device FmParams)``, ``("tiered", step,
    (w0, params ColdStore))`` or ``("quant", step, (w0, QuantTable))``
    — whichever format the checkpoint directory holds (the formats are
    mutually exclusive; the save paths enforce that).  Raises if none
    exists.  A quant.npz must match the configured
    ``serve_table_dtype`` / ``quant_chunk`` — refused loudly on
    mismatch (scale indexing is chunk-derived; serving a table under
    the wrong descriptor would silently mis-score).

    Dense restores carry the local mesh's TARGET shardings (the same
    template discipline the trainer/old-predict used): orbax places
    each shard directly where this topology wants it, so a checkpoint
    saved on more devices restores fine on fewer — restoring through
    sharding-less host templates would fall back to the
    sharding-from-file path orbax documents as topology-unsafe.
    """
    if checkpoint.exists_tiered(cfg.model_file):
        step, scalars, stores = checkpoint.restore_tiered(cfg.model_file)
        payload = stores["table"]
        want = tiered_lib._virtual_descriptor(cfg, "table")
        got = payload.get("descriptor")
        if got is not None and got != want:
            raise ValueError(
                f"tiered checkpoint store 'table' was written under a "
                f"different init ({got} != {want}); seed/"
                "init_value_range must match the run that saved it"
            )
        store = tiered_lib._virtual_store(cfg, "table")
        store.import_overlay(payload)
        return "tiered", step, (float(scalars["w0"]), store)
    got = checkpoint.restore_quant(cfg.model_file)
    if got is not None:
        step, w0, qt = got
        desc = qt.descriptor()
        if (
            desc["vocab"] != cfg.vocabulary_size
            or desc["dim"] != cfg.embedding_dim
        ):
            raise ValueError(
                f"quantized checkpoint table is [{desc['vocab']}, "
                f"{desc['dim']}] but the config wants "
                f"[{cfg.vocabulary_size}, {cfg.embedding_dim}]"
            )
        if qt.dtype != cfg.serve_table_dtype:
            raise ValueError(
                f"quantized checkpoint at {cfg.model_file} is "
                f"{qt.dtype} but serve_table_dtype="
                f"{cfg.serve_table_dtype}; set the knob to the "
                "checkpoint's dtype or convert it "
                "(python -m tools.convert_checkpoint)"
            )
        return "quant", step, (np.float32(w0), qt)
    if checkpoint.exists(cfg.model_file):
        mesh = mesh if mesh is not None else mesh_lib.make_mesh(cfg)
        param_sh = mesh_lib.param_sharding(mesh)
        shapes = jax.eval_shape(
            partial(fm.init_params, cfg=cfg), jax.random.PRNGKey(0)
        )
        template = jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(
                s.shape, s.dtype, sharding=sh
            ),
            shapes, param_sh,
        )
        params, step = checkpoint.restore_params(cfg.model_file, template)
        return "dense", step, fm.FmParams(*params)
    raise ValueError(
        f"no servable checkpoint at {cfg.model_file} (neither the "
        "dense params/opt dirs nor a tiered.npz overlay)"
    )


def make_scorer(cfg: FmConfig, mesh=None, telemetry=None, writer=None,
                extra_rungs=()):
    """Build the right scorer for whatever ``cfg.model_file`` holds."""
    fmt, step, model = load_model(cfg, mesh=mesh)
    if fmt == "tiered":
        w0, store = model
        return OverlayScorer(
            cfg, w0, store, mesh=mesh, telemetry=telemetry,
            writer=writer, extra_rungs=extra_rungs, step=step,
        )
    # "dense" passes fm.FmParams, "quant" a (w0, QuantTable) pair —
    # FixedShapeScorer places either per serve_table_dtype.
    return FixedShapeScorer(
        cfg, model, mesh=mesh, telemetry=telemetry, writer=writer,
        extra_rungs=extra_rungs, step=step,
    )
