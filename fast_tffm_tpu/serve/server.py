"""The scoring endpoint: HTTP front door, hot-swap watcher, lifecycle.

:class:`ServeServer` mounts the batcher behind a stdlib
ThreadingHTTPServer (the same server discipline as ``obs/status.py`` —
daemon thread, read-only observability routes, degrade-don't-die):

- ``POST /score`` — body is libsvm/ffm text, one example per line in
  exactly the ``predict_files`` format (label column present but
  ignored; lines whose first token contains ``:`` are accepted
  label-less).  Response: one score per non-blank line, ``%.6f`` —
  byte-identical formatting to offline ``predict``'s ``score_path``.
- ``GET /metrics`` / ``/status`` / ``/healthz`` — the live
  observability surface, rendered by the same
  ``obs.status.render_prometheus`` the trainer's endpoint uses; all
  ``serve.*`` instruments plus a ``serve`` record block (qps, latency
  percentiles, batch fill, swaps) show up as ``tffm_serve_*`` series.

:class:`CheckpointWatcher` is the warm hot-swap driver: it polls the
``serve_manifest.json`` the trainer's save path publishes (the manifest
is written AFTER the checkpoint files, so a published step is always a
complete checkpoint), reloads the params into standby buffers
off-traffic, and calls ``scorer.swap`` — zero recompiles (shapes
unchanged), zero dropped requests (one reference swap between
dispatches).  A reload that races the NEXT save simply fails, warns,
and retries at the next poll.

:func:`serve` builds the whole stack from an :class:`FmConfig`
(scorer -> warmup -> batcher -> watcher -> HTTP) and returns a
:class:`ServeHandle`; :func:`serve_forever` is the CLI entry
(``run_tffm.py serve <cfg>``).
"""

from __future__ import annotations

import logging
import threading
import time
from http.server import ThreadingHTTPServer
from typing import Optional

import numpy as np

from fast_tffm_tpu import obs
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data import libsvm
from fast_tffm_tpu.obs.status import QuietHandler
from fast_tffm_tpu.serve.batcher import ServeBatcher
from fast_tffm_tpu.serve import scorer as scorer_lib
from fast_tffm_tpu.train import checkpoint

log = logging.getLogger(__name__)

# POST /score body cap: far above any sane scoring request (a 64 MiB
# libsvm body is ~1M examples), far below what would hurt the host.
_MAX_BODY_BYTES = 64 << 20

__all__ = [
    "CheckpointWatcher", "ServeHandle", "ServeServer", "parse_request",
    "serve", "serve_forever",
]


def parse_request(text: str, cfg: FmConfig):
    """Request body -> ``(ids, vals, fields, n, truncated)`` arrays.

    One example per non-blank, non-comment line, ``predict_files``
    format.  A line whose FIRST token contains ``:`` is treated as
    label-less (scoring clients rarely have labels); anything else goes
    through :func:`libsvm.parse_line` unchanged, so request files and
    predict files are interchangeable.  NOTE the inherent libsvm
    ambiguity this rule resolves deterministically: a line of BARE
    feature ids ("123 456 789") is indistinguishable from a labeled
    line, so its first token is always read as the label — bare-id
    clients must send an explicit label column (or ``id:1`` tokens);
    documented in SERVING.md.  Raises ValueError (-> HTTP 400) on a
    malformed line.  ``truncated`` counts feature occurrences
    dropped by ``max_features`` — a truncated example scores as a
    DIFFERENT example, the same data-integrity event the ingest path
    surfaces as ``ingest.truncated_features`` (the server counts it as
    ``serve.truncated_features``).
    """
    examples = []
    for lineno, line in enumerate(text.splitlines(), 1):
        stripped = line.strip()
        if not stripped or stripped.startswith("#"):
            continue
        if ":" in stripped.split(None, 1)[0]:
            stripped = "0 " + stripped
        try:
            ex = libsvm.parse_line(
                stripped, cfg.vocabulary_size, cfg.hash_feature_id,
                cfg.field_num,
            )
        except ValueError as e:
            raise ValueError(f"line {lineno}: {e}") from e
        if ex is not None:
            examples.append(ex)
    n = len(examples)
    F = cfg.max_features
    ids = np.zeros((n, F), np.int32)
    vals = np.zeros((n, F), np.float32)
    fields = np.zeros((n, F), np.int32)
    truncated = 0
    for i, ex in enumerate(examples):
        k = min(len(ex.ids), F)
        truncated += len(ex.ids) - k
        ids[i, :k] = ex.ids[:k]
        vals[i, :k] = ex.vals[:k]
        fields[i, :k] = ex.fields[:k]
    return ids, vals, fields, n, truncated


class CheckpointWatcher:
    """Poll the save-path manifest; hot-swap the scorer on a new step.

    ``seen`` is the baseline manifest the currently-served params came
    from; the owner should capture it BEFORE loading the checkpoint
    (serve() does), so a save landing during load/warmup is still
    picked up at the first poll instead of being silently baselined
    away.  Omitted -> read at construction (direct/test use).
    """

    def __init__(self, cfg: FmConfig, scorer, poll_secs: float,
                 on_swap=None, seen=None):
        self._cfg = cfg
        self._scorer = scorer
        self._poll = max(0.05, float(poll_secs))
        self._on_swap = on_swap
        self._seen = (
            seen if seen is not None
            else checkpoint.read_manifest(cfg.model_file)
        )
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="tffm-serve-watcher", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            try:
                self._check_once()
            except Exception as e:  # noqa: BLE001 - retry next poll
                log.warning(
                    "checkpoint watcher: reload failed (%s); will "
                    "retry next poll", e,
                )

    def _check_once(self) -> None:
        man = checkpoint.read_manifest(self._cfg.model_file)
        if man is None or man == self._seen:
            return
        try:
            fmt, step, model = scorer_lib.load_model(
                self._cfg, mesh=self._scorer.mesh
            )
            scorer = self._scorer
            if fmt == "tiered" and isinstance(
                scorer, scorer_lib.OverlayScorer
            ):
                scorer.swap(*model, step=step)
            elif fmt in ("dense", "quant") and isinstance(
                scorer, scorer_lib.FixedShapeScorer
            ):
                # A dense checkpoint swaps into any table dtype (a
                # quantized scorer re-quantizes it off-traffic); a
                # quant checkpoint must match the scorer's
                # dtype/chunk — mismatches raise ValueError below.
                scorer.swap(model, step=step)
            else:
                log.warning(
                    "checkpoint at %s changed FORMAT (%s) mid-serve; "
                    "a running server cannot cross dense<->tiered — "
                    "restart to pick it up",
                    self._cfg.model_file, fmt,
                )
                self._seen = man
                return
        except ValueError as e:
            # A ValueError out of load_model/swap is a PERMANENT
            # config<->checkpoint contradiction (serve_table_dtype or
            # quant_chunk mismatch, shape mismatch, overlay descriptor
            # drift) — re-reading a multi-GB table every poll would
            # never fix it.  Baseline the manifest like the
            # format-flip branch: warn once, keep serving the current
            # params, pick up the NEXT save.
            log.warning(
                "checkpoint at %s cannot be served under this config "
                "(%s); keeping the current params — fix the config or "
                "republish, a restart is NOT needed for the next "
                "compatible save", self._cfg.model_file, e,
            )
            self._seen = man
            return
        self._seen = man
        if self._on_swap is not None:
            self._on_swap(step)

    def close(self) -> None:
        self._stop.set()
        self._thread.join()


class ServeServer:
    """HTTP front door: ``POST /score`` + the observability routes."""

    def __init__(self, port: int, batcher: ServeBatcher, cfg: FmConfig,
                 build, telemetry=None, host: str = "127.0.0.1",
                 timeout_s: float = 30.0):
        tel = telemetry if telemetry is not None else obs.NULL
        requests_c = tel.counter("serve.http_requests")
        truncated_c = tel.counter("serve.truncated_features")
        # Per-request libsvm-text parse time: PR 9 flagged text parsing
        # as measurable host latency at small requests — this timer
        # makes it a measured number (/metrics + the bench serve
        # section) instead of an assumption, and the datum a future
        # binary transport would be judged against.
        parse_t = tel.timer("serve.parse")
        server = self

        class Handler(QuietHandler):
            def do_POST(self) -> None:  # noqa: N802 - http.server API
                requests_c.add()
                if self.path.partition("?")[0] != "/score":
                    self._send(404, b"not found\n", "text/plain")
                    return
                if "Content-Length" not in self.headers:
                    # Without a length the body is unreadable here
                    # (chunked encoding): answering 200-empty would
                    # silently drop the client's examples.
                    self._send(
                        411, b"Content-Length required (chunked "
                             b"transfer is not supported)\n",
                        "text/plain",
                    )
                    return
                try:
                    length = int(self.headers["Content-Length"])
                except ValueError:
                    self._send(400, b"bad Content-Length\n", "text/plain")
                    return
                # The client's length is untrusted input on an
                # unauthenticated endpoint: a negative value would
                # read-to-EOF (handler thread pinned until the client
                # hangs up), an absurd one would buffer it all.
                if length < 0:
                    self._send(400, b"bad Content-Length\n", "text/plain")
                    return
                if length > _MAX_BODY_BYTES:
                    self._send(
                        413, f"request body over the "
                             f"{_MAX_BODY_BYTES >> 20} MiB cap; split "
                             f"it\n".encode(), "text/plain",
                    )
                    return
                try:
                    text = self.rfile.read(length).decode()
                    with parse_t.time():
                        ids, vals, fields, n, truncated = parse_request(
                            text, cfg
                        )
                except (ValueError, UnicodeDecodeError) as e:
                    self._send(
                        400, f"bad request: {e}\n".encode(), "text/plain"
                    )
                    return
                if truncated:
                    # Same integrity signal the ingest path counts: a
                    # truncated example scores as a different example.
                    truncated_c.add(truncated)
                if n == 0:
                    self._send(200, b"", "text/plain")
                    return
                try:
                    scores = batcher.score(
                        ids, vals,
                        fields if cfg.field_num else None,
                        timeout=timeout_s,
                    )
                except Exception as e:  # noqa: BLE001 - report, don't die
                    self._send(
                        503, f"scoring failed: {e}\n".encode(),
                        "text/plain",
                    )
                    return
                body = "".join(f"{s:.6f}\n" for s in scores).encode()
                self._send(200, body, "text/plain")

            def do_GET(self) -> None:  # noqa: N802 - http.server API
                requests_c.add()
                path = self.path.partition("?")[0]
                if self._get_observability(path, server._build):
                    return
                self._send(404, b"not found\n", "text/plain")

        self._build = build
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="tffm-serve-http",
            daemon=True,
        )
        self._thread.start()
        self._closed = False

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._httpd.shutdown()
        self._thread.join()
        self._httpd.server_close()


class ServeHandle:
    """One running serving stack; ``close()`` tears it down in order
    (HTTP stops accepting, batcher drains/fails, watcher stops, final
    record written)."""

    def __init__(self, cfg, scorer, batcher, server, watcher, telemetry,
                 writer, heartbeat, build):
        self.cfg = cfg
        self.scorer = scorer
        self.batcher = batcher
        self.server = server
        self.watcher = watcher
        self.telemetry = telemetry
        self.port = server.port
        self._writer = writer
        self._heartbeat = heartbeat
        self._build = build
        self._closed = False

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.server.close()
        if self.watcher is not None:
            self.watcher.close()
        self.batcher.close()
        if self._heartbeat is not None:
            self._heartbeat.close()
        if self._writer is not None:
            try:
                final = self._build("final")
                if final is not None:
                    self._writer.write(final)
            except Exception as e:  # noqa: BLE001 - teardown best-effort
                log.warning("serve final record write failed: %s", e)
            self._writer.close()


def _serve_block(snap: dict, scorer, batcher, wall: float) -> dict:
    """The ``serve`` record block: flat, numeric, host-side only —
    rendered as ``tffm_serve_*`` by /metrics and summarized by
    tools/report.py.  ``snap`` is the one telemetry snapshot the whole
    record is built from (one instrument-lock walk per scrape, and the
    block can never disagree with ``stages``)."""
    counters = snap.get("counters") or {}
    timers = snap.get("timers") or {}
    gauges = snap.get("gauges") or {}
    lat = timers.get("serve.latency") or {}
    requests = int(counters.get("serve.requests", 0))
    out = {
        "requests": requests,
        "examples": int(counters.get("serve.examples", 0)),
        "batches": int(counters.get("serve.batches", 0)),
        "qps": round(requests / wall, 2) if wall > 0 else 0.0,
        "batch_fill": round(batcher.batch_fill, 6),
        "swaps": int(counters.get("serve.swaps", 0)),
        "compiles": int(scorer.compiles),
        "steady_compiles": int(scorer.steady_compiles),
        "recompiles_unexpected": int(
            counters.get("serve.recompiles_unexpected", 0)
        ),
        "truncated_features": int(
            counters.get("serve.truncated_features", 0)
        ),
    }
    # Quantized-table accounting, emitted only when the scorer owns
    # the gauges (FixedShapeScorer): the device-resident table's real
    # byte footprint and the max |served_fp32 − served_quant| probe
    # error from the last placement (0 = fp32 serving IS the
    # reference, −1 = unknown).  An OverlayScorer registers neither —
    # defaulting its error to 0 would CLAIM exactness for a quantized
    # cold store it never measured.
    if "serve.table_bytes" in gauges:
        out["table_mb"] = round(
            gauges["serve.table_bytes"] / (1 << 20), 3
        )
    if "serve.quant_error_max" in gauges:
        out["quant_error_max"] = round(
            float(gauges["serve.quant_error_max"]), 6
        )
    for key in ("mean_ms", "p50_ms", "p95_ms", "p99_ms", "max_ms"):
        if key in lat:
            out[key] = lat[key]
    parse = timers.get("serve.parse") or {}
    if "p50_ms" in parse:
        out["parse_p50_ms"] = parse["p50_ms"]
    return out


def serve(cfg: FmConfig, mesh=None, port: Optional[int] = None
          ) -> ServeHandle:
    """Build and start the full serving stack from a config.

    ``port`` overrides ``cfg.serve_port`` (tests pass 0 for an
    OS-assigned port; the bound port is ``handle.port``).
    """
    writer = (
        obs.JsonlWriter(cfg.metrics_file) if cfg.metrics_file else None
    )
    telemetry = obs.Telemetry(enabled=cfg.telemetry)
    # Watcher baseline BEFORE the load: a checkpoint published while we
    # load/warm up must look NEW to the first poll (the scorer may or
    # may not have caught it; re-swapping to the same step is a cheap
    # no-op, serving stale params forever is not).
    manifest_baseline = checkpoint.read_manifest(cfg.model_file)
    try:
        scorer = scorer_lib.make_scorer(
            cfg, mesh=mesh, telemetry=telemetry, writer=writer
        )
        n_compiles = scorer.warmup()
    except BaseException:
        # No servable checkpoint / warmup failure: close the metrics
        # writer behind the raise (callers retrying against a racing
        # model dir must not accumulate leaked fds).
        if writer is not None:
            writer.close()
        raise
    log.info(
        "scorer ready: checkpoint step %d, ladder %s, %d rung(s) "
        "precompiled — steady-state serving performs zero compiles",
        scorer.step, list(scorer.ladder), n_compiles,
    )
    batcher = ServeBatcher(
        scorer, max_batch_wait_ms=cfg.max_batch_wait_ms,
        queue_size=cfg.queue_size, telemetry=telemetry,
    )
    t0 = time.time()

    def build(kind: str = "status"):
        now = time.time()
        wall = max(now - t0, 1e-9)
        snap = telemetry.snapshot()
        rec = {
            "record": kind,
            "time": now,
            "elapsed": round(wall, 3),
            "step": scorer.step,
            "serve": _serve_block(snap, scorer, batcher, wall),
            "stages": snap,
        }
        return rec

    if writer is not None:
        writer.write({
            "record": "run_header",
            "mode": "serve",
            "time": t0,
            "model_file": cfg.model_file,
            "resume_step": scorer.step,
            "serve_batch_sizes": list(scorer.ladder),
            "max_batch_wait_ms": cfg.max_batch_wait_ms,
            "serve_poll_secs": cfg.serve_poll_secs,
            "batch_size": cfg.batch_size,
            "telemetry": cfg.telemetry,
            "heartbeat_secs": cfg.heartbeat_secs,
        })
    heartbeat = None
    if cfg.heartbeat_secs > 0:
        heartbeat = obs.Heartbeat(
            cfg.heartbeat_secs, lambda: build("heartbeat"),
            writer=writer,
        )
    watcher = None
    try:
        if cfg.serve_poll_secs > 0:
            watcher = CheckpointWatcher(
                cfg, scorer, cfg.serve_poll_secs,
                seen=manifest_baseline,
            )
        server = ServeServer(
            cfg.serve_port if port is None else port,
            batcher, cfg, build, telemetry=telemetry, host=cfg.serve_host,
        )
    except BaseException:
        # A taken port (or watcher failure) must not leak the batcher
        # dispatcher / watcher / heartbeat threads behind the raise.
        if watcher is not None:
            watcher.close()
        batcher.close()
        if heartbeat is not None:
            heartbeat.close()
        if writer is not None:
            writer.close()
        raise
    log.info(
        "scoring endpoint listening on %s:%d (POST /score; GET "
        "/metrics, /status, /healthz, /debug/threadz)",
        cfg.serve_host, server.port,
    )
    return ServeHandle(
        cfg, scorer, batcher, server, watcher, telemetry, writer,
        heartbeat, build,
    )


def serve_forever(cfg: FmConfig) -> int:
    """CLI entry: serve until interrupted (SIGINT -> clean close)."""
    handle = serve(cfg)
    print(f"serving on {cfg.serve_host}:{handle.port}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        log.info("interrupted; shutting down the scoring endpoint")
    finally:
        handle.close()
    return 0
