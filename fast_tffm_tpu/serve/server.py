"""The scoring endpoint: HTTP front door, hot-swap watcher, lifecycle.

:class:`ServeServer` mounts the batcher behind a stdlib
ThreadingHTTPServer (the same server discipline as ``obs/status.py`` —
daemon thread, read-only observability routes, degrade-don't-die):

- ``POST /score`` — body is libsvm/ffm text, one example per line in
  exactly the ``predict_files`` format (label column present but
  ignored; lines whose first token contains ``:`` are accepted
  label-less).  Response: one score per non-blank line, ``%.6f`` —
  byte-identical formatting to offline ``predict``'s ``score_path``.
- ``POST /score_bin`` — the binary request transport: one
  length-prefixed little-endian frame of id/value/field arrays (layout
  at the codec below and in SERVING.md), decoded by ``np.frombuffer``
  so the hot path skips text parsing entirely; scores come back as one
  binary frame.  Bitwise-identical scores to ``/score`` for the same
  examples.  ``serve_transport`` gates which of the two are enabled.
- ``POST /reload`` / ``/promote`` / ``/rollback`` — the admin swap
  surface the router's canary promotion drives: reload the current
  manifest's checkpoint keeping the replaced params restorable, close
  the rollback window, or restore them.
- ``GET /metrics`` / ``/status`` / ``/healthz`` — the live
  observability surface, rendered by the same
  ``obs.status.render_prometheus`` the trainer's endpoint uses; all
  ``serve.*`` instruments plus a ``serve`` record block (qps, latency
  percentiles, batch fill, swaps) show up as ``tffm_serve_*`` series.

:class:`CheckpointWatcher` is the warm hot-swap driver: it polls the
``serve_manifest.json`` the trainer's save path publishes (the manifest
is written AFTER the checkpoint files, so a published step is always a
complete checkpoint), reloads the params into standby buffers
off-traffic, and calls ``scorer.swap`` — zero recompiles (shapes
unchanged), zero dropped requests (one reference swap between
dispatches).  A reload that races the NEXT save simply fails, warns,
and retries at the next poll.

:func:`serve` builds the whole stack from an :class:`FmConfig`
(scorer -> warmup -> batcher -> watcher -> HTTP) and returns a
:class:`ServeHandle`; :func:`serve_forever` is the CLI entry
(``run_tffm.py serve <cfg>``).
"""

from __future__ import annotations

import json
import logging
import os
import signal
import threading
import time
from typing import Optional

import numpy as np

from fast_tffm_tpu import obs, platform
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.obs.status import (
    ObsHTTPServer, PooledHTTPServer, QuietHandler,
)
from fast_tffm_tpu.obs.trace import NULL_TRACER, Tracer
from fast_tffm_tpu.serve import wire
from fast_tffm_tpu.serve.batcher import ServeBatcher
from fast_tffm_tpu.serve import scorer as scorer_lib
from fast_tffm_tpu.serve.slo import SloTracker
from fast_tffm_tpu.serve.textparse import ParseScratchPool, parse_request
from fast_tffm_tpu.train import checkpoint

log = logging.getLogger(__name__)

# Backward-compatible spellings: the codec (and the shared POST body
# cap) live in serve/wire.py — jax-free so the router process can
# decode frames without a jax import — and re-export here where the
# serving tests and clients historically found them.
_MAX_BODY_BYTES = wire.MAX_BODY_BYTES
BIN_MAGIC = wire.BIN_MAGIC
decode_bin_request = wire.decode_bin_request
decode_bin_response = wire.decode_bin_response
encode_bin_request = wire.encode_bin_request
encode_bin_response = wire.encode_bin_response

__all__ = [
    "BIN_MAGIC", "CheckpointWatcher", "ParseScratchPool", "ServeHandle",
    "ServeServer", "decode_bin_request", "decode_bin_response",
    "encode_bin_request", "encode_bin_response", "parse_request",
    "reload_scorer", "serve", "serve_forever",
]

# parse_request lives in serve/textparse.py now (the vectorized batch
# parser + its per-line fallback/oracle and the scratch pool); it is
# re-exported above because the serving tests and embedders have always
# imported it from here.


def reload_scorer(cfg: FmConfig, scorer, keep_prev: bool = False) -> int:
    """Reload ``cfg.model_file``'s checkpoint into the running scorer
    (standby buffers, then one reference swap — never torn).  Returns
    the new step.  Raises ValueError on a config<->checkpoint
    contradiction, including a dense<->tiered FORMAT flip a running
    scorer cannot cross.  Shared by the poll watcher, the ``/reload``
    admin route, and the router's canary protocol (which passes
    ``keep_prev=True`` to hold the rollback window open)."""
    fmt, step, model = scorer_lib.load_model(cfg, mesh=scorer.mesh)
    if fmt == "tiered" and isinstance(scorer, scorer_lib.OverlayScorer):
        scorer.swap(*model, step=step, keep_prev=keep_prev)
    elif fmt in ("dense", "quant") and isinstance(
        scorer, scorer_lib.FixedShapeScorer
    ):
        # A dense checkpoint swaps into any table dtype (a quantized
        # scorer re-quantizes it off-traffic); a quant checkpoint must
        # match the scorer's dtype/chunk — load_model/swap raise
        # ValueError on mismatch.
        scorer.swap(model, step=step, keep_prev=keep_prev)
    else:
        raise ValueError(
            f"checkpoint at {cfg.model_file} changed FORMAT ({fmt}) "
            "mid-serve; a running server cannot cross dense<->tiered "
            "— restart to pick it up"
        )
    return step


class CheckpointWatcher:
    """Poll the save-path manifest; hot-swap the scorer on a new step.

    ``seen`` is the baseline manifest the currently-served params came
    from; the owner should capture it BEFORE loading the checkpoint
    (serve() does), so a save landing during load/warmup is still
    picked up at the first poll instead of being silently baselined
    away.  Omitted -> read at construction (direct/test use).
    """

    def __init__(self, cfg: FmConfig, scorer, poll_secs: float,
                 on_swap=None, seen=None):
        self._cfg = cfg
        self._scorer = scorer
        self._poll = max(0.05, float(poll_secs))
        self._on_swap = on_swap
        self._seen = (
            seen if seen is not None
            else checkpoint.read_manifest(cfg.model_file)
        )
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="tffm-serve-watcher", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            try:
                self._check_once()
            except Exception as e:  # noqa: BLE001 - retry next poll
                log.warning(
                    "checkpoint watcher: reload failed (%s); will "
                    "retry next poll", e,
                )

    def _check_once(self) -> None:
        man = checkpoint.read_manifest(self._cfg.model_file)
        if man is None or man == self._seen:
            return
        try:
            step = reload_scorer(self._cfg, self._scorer)
        except ValueError as e:
            # A ValueError out of load_model/swap is a PERMANENT
            # config<->checkpoint contradiction (serve_table_dtype or
            # quant_chunk mismatch, shape mismatch, overlay descriptor
            # drift) — re-reading a multi-GB table every poll would
            # never fix it.  Baseline the manifest like the
            # format-flip branch: warn once, keep serving the current
            # params, pick up the NEXT save.
            log.warning(
                "checkpoint at %s cannot be served under this config "
                "(%s); keeping the current params — fix the config or "
                "republish, a restart is NOT needed for the next "
                "compatible save", self._cfg.model_file, e,
            )
            self._seen = man
            return
        self._seen = man
        if self._on_swap is not None:
            self._on_swap(step)

    def close(self) -> None:
        self._stop.set()
        self._thread.join()


class ServeServer:
    """HTTP front door: ``POST /score`` (libsvm text) + ``POST
    /score_bin`` (the binary frame transport, gated by
    ``serve_transport``), the admin routes the router's canary
    protocol drives (``/reload`` / ``/promote`` / ``/rollback``), and
    the observability routes."""

    def __init__(self, port: int, batcher: ServeBatcher, cfg: FmConfig,
                 build, telemetry=None, host: str = "127.0.0.1",
                 timeout_s: float = 30.0, scorer=None, tracer=None,
                 sampler=None, slo=None, on_reload=None,
                 on_rollback=None, capture=None, incident=None):
        tel = telemetry if telemetry is not None else obs.NULL
        tracer = tracer if tracer is not None else NULL_TRACER
        # Request-id mint + trace-sampling coin flip for DIRECT
        # traffic (a router stamps ids before they arrive; a
        # single-process server is its own front door).
        sampler = sampler if sampler is not None else wire.RequestSampler(
            cfg.serve_trace_sample, enabled=tracer.enabled, tag="s"
        )
        requests_c = tel.counter("serve.http_requests")
        truncated_c = tel.counter("serve.truncated_features")
        # Per-request libsvm-text parse time: PR 9 flagged text parsing
        # as measurable host latency at small requests — this timer
        # made it a measured number, and the binary transport's
        # serve.parse_bin twin is the datum that shows what removing
        # the text parse actually buys (bench: serve_parse_p50_ms vs
        # serve_bin_p50_ms).
        parse_t = tel.timer("serve.parse")
        parse_bin_t = tel.timer("serve.parse_bin")
        # Recycled per-request parse scratch (textparse.py): the text
        # path's arrays come from here and go back via the batcher's
        # on_done hook — steady-state text scoring allocates near-zero
        # per request.  The binary transport decodes straight out of
        # the request body (np.frombuffer views) and stays unpooled.
        parse_pool = ParseScratchPool(cfg.max_features, telemetry=tel)
        # The admin swap surface is driven over HTTP by the router's
        # canary protocol; one at a time (a reload stages a whole
        # standby table — two concurrent ones would race the rollback
        # window).
        admin_lock = threading.Lock()
        server = self

        def score_arrays(handler, ids, vals, fields, n, truncated,
                         encode, rid=None, on_done=None) -> None:
            """Shared tail of both transports: count integrity events,
            batch-score, encode the response.  ``rid`` (a sampled or
            client-supplied request id) is echoed in the response's
            ``X-Request-Id`` header and closes the request's span
            chain with a ``serve.respond`` span.  ``on_done`` is the
            pooled-scratch release hook: from here on the BATCHER owns
            firing it (exactly once, when its dispatcher stops reading
            the arrays — a client-side timeout must NOT release a
            buffer the dispatcher still holds); the n == 0 early-out
            never submits, so it releases directly."""
            if truncated:
                # Same integrity signal the ingest path counts: a
                # truncated example scores as a different example.
                truncated_c.add(truncated)
            rid_hdr = {"X-Request-Id": rid} if rid is not None else None
            if n == 0:
                if on_done is not None:
                    on_done()
                ctype, body = encode(np.zeros((0,), np.float32))
                handler._send(200, body, ctype, headers=rid_hdr)
                return
            # Traffic capture (serve_capture_sample): the request frame
            # is encoded NOW, while this handler still owns the arrays
            # — the batcher releases pooled text-parse scratch the
            # moment its dispatcher stops reading it.  Canonical form
            # (post-pad, post-modulo) makes re-decoding idempotent, so
            # replaying the frame reproduces the response bitwise.
            # Unsampled requests pay one attribute compare.
            cap_req = None
            if capture is not None and capture.sample():
                try:
                    cap_req = wire.encode_bin_request(
                        ids[:n], vals[:n],
                        fields[:n]
                        if (cfg.field_num and fields is not None)
                        else None,
                    )
                except Exception as e:  # noqa: BLE001 - forensics only
                    log.warning("capture encode failed: %s", e)
            try:
                scores = batcher.score(
                    ids, vals,
                    fields if cfg.field_num else None,
                    timeout=timeout_s, rid=rid, on_done=on_done,
                )
            except Exception as e:  # noqa: BLE001 - report, don't die
                if slo is not None:
                    # The batcher's ledger only sees requests its
                    # dispatcher finishes; an HTTP-layer failure (a
                    # scoring timeout, a closed batcher) is a 503 the
                    # CLIENT saw — without this, a 503 storm would
                    # read as burn_rate 0.
                    slo.observe(False)
                handler._send(
                    503, f"scoring failed: {e}\n".encode(),
                    "text/plain", headers=rid_hdr,
                )
                return
            if cap_req is not None:
                capture.write(cap_req, encode_bin_response(scores))
            t_r0 = time.perf_counter()
            ctype, body = encode(scores)
            handler._send(200, body, ctype, headers=rid_hdr)
            if rid is not None:
                # Chain tail: scores -> encoded -> written back to the
                # client; the flow end ("f") binds the arrow from the
                # dispatch step to this span.
                tracer.emit(
                    "serve.respond", t_r0,
                    time.perf_counter() - t_r0,
                    args={"rid": rid, "n": n}, flow=("f", rid),
                )

        def encode_text(scores):
            return "text/plain", "".join(
                f"{s:.6f}\n" for s in scores
            ).encode()

        def encode_bin(scores):
            return "application/octet-stream", encode_bin_response(scores)

        class Handler(QuietHandler):
            def do_POST(self) -> None:  # noqa: N802 - http.server API
                requests_c.add()
                path, _, query = self.path.partition("?")
                if path in ("/reload", "/promote", "/rollback"):
                    self._do_admin(path, query)
                    return
                if path == "/incident":
                    self._post_incident(query, incident)
                    return
                if path not in ("/score", "/score_bin"):
                    self._send(404, b"not found\n", "text/plain")
                    return
                want = "text" if path == "/score" else "bin"
                if cfg.serve_transport not in (want, "both"):
                    self._send(
                        404, f"transport {want!r} disabled "
                             f"(serve_transport="
                             f"{cfg.serve_transport})\n".encode(),
                        "text/plain",
                    )
                    return
                body = self._read_body(_MAX_BODY_BYTES)
                if body is None:
                    return  # error response already sent
                # Request id: the X-Request-Id header (either
                # transport), overridden by the binary frame's own
                # trailer (the router stamps SAMPLED frames there).
                # Invalid ids (empty/oversized/control chars) are
                # ignored, not errors — tracing must never fail a
                # scoring request.
                rid = self.headers.get("X-Request-Id")
                if rid is not None and not wire.valid_request_id(rid):
                    rid = None
                on_done = None
                try:
                    if path == "/score":
                        with parse_t.time():
                            parsed = parse_request(
                                body.decode(), cfg, pool=parse_pool
                            )
                        ids, vals, fields, n, truncated = parsed
                        on_done = lambda i=ids: parse_pool.release(i)  # noqa: E731
                    else:
                        with parse_bin_t.time():
                            (ids, vals, fields, n, truncated,
                             frame_rid) = decode_bin_request(body, cfg)
                        # Same sanitization as the header path: the
                        # rid echoes into a response HEADER, so a
                        # trailer smuggling CR/LF (or non-latin-1
                        # bytes send_header can't write) must be
                        # dropped, never reflected.
                        if frame_rid is not None and \
                                wire.valid_request_id(frame_rid):
                            rid = frame_rid
                except (ValueError, UnicodeDecodeError) as e:
                    self._send(
                        400, f"bad request: {e}\n".encode(), "text/plain"
                    )
                    return
                if rid is None and sampler.sample():
                    # Direct traffic with no upstream id: this server
                    # is the front door, so it mints (and samples)
                    # itself.  Unsampled requests never reach here
                    # with any id work done.
                    rid = sampler.mint()
                score_arrays(
                    self, ids, vals, fields, n, truncated,
                    encode_text if path == "/score" else encode_bin,
                    rid=rid, on_done=on_done,
                )

            def _do_admin(self, path: str, query: str) -> None:
                """The canary-protocol swap surface.  ``/reload``
                loads the CURRENT manifest's checkpoint into standby
                buffers and swaps; only ``/reload?keep_prev=1`` (the
                router's canary reload) retains the replaced params
                for ``/rollback`` — a plain reload must not pin a
                second table in device memory (nothing in a
                non-canary deployment would ever ``/promote`` it
                away), and without a retained window ``/rollback`` is
                a 409, so a stray admin call cannot flip the served
                model.  All three answer JSON with the served step."""
                if scorer is None:
                    self._send(
                        503, b"no admin scorer on this endpoint\n",
                        "text/plain",
                    )
                    return
                # Consume a (normally empty) body so keep-alive stays
                # intact for admin clients that send one.
                if self._read_body(_MAX_BODY_BYTES) is None:
                    return
                with admin_lock:
                    try:
                        if path == "/reload":
                            reload_scorer(
                                cfg, scorer,
                                keep_prev="keep_prev=1" in query,
                            )
                            if on_reload is not None:
                                # The served params now come from the
                                # current manifest; the skew reference
                                # follows (canary replicas run
                                # watcher-less, so this is their only
                                # reference-refresh path).
                                on_reload()
                        elif path == "/promote":
                            scorer.promote()
                            if on_reload is not None:
                                on_reload()
                        else:
                            if not scorer.rollback():
                                self._send(
                                    409, b"nothing to roll back to (no "
                                         b"keep-prev swap is open)\n",
                                    "text/plain",
                                )
                                return
                            if on_rollback is not None:
                                # The served params just reverted to
                                # the PRE-canary checkpoint; the skew
                                # reference reverts with them (its
                                # manifest is gone from disk, so this
                                # restores the stashed copy).
                                on_rollback()
                    except ValueError as e:
                        self._send(
                            409, f"{e}\n".encode(), "text/plain"
                        )
                        return
                    except Exception as e:  # noqa: BLE001 - report
                        self._send(
                            500, f"{path} failed: {e}\n".encode(),
                            "text/plain",
                        )
                        return
                    body = (json.dumps({"step": scorer.step}) + "\n"
                            ).encode()
                self._send(200, body, "application/json")

            def do_GET(self) -> None:  # noqa: N802 - http.server API
                requests_c.add()
                path = self.path.partition("?")[0]
                if self._get_observability(path, server._build):
                    return
                self._send(404, b"not found\n", "text/plain")

        self._build = build
        self.parse_pool = parse_pool
        # Pooled front end by default; serve_http_threads = 0 keeps
        # the r14 thread-per-connection server, byte-identical.  Two
        # plain assignments (not one conditional expression) so the
        # lifecycle lint sees both constructor bindings.
        if cfg.serve_http_threads > 0:
            self._httpd = PooledHTTPServer(
                (host, port), Handler,
                pool_size=cfg.serve_http_threads,
                acceptors=cfg.serve_http_acceptors,
            )
        else:
            self._httpd = ObsHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="tffm-serve-http",
            daemon=True,
        )
        self._thread.start()
        self._closed = False

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._httpd.shutdown()
        self._thread.join()
        self._httpd.server_close()


class ServeHandle:
    """One running serving stack; ``close()`` tears it down in order
    (HTTP stops accepting, batcher drains/fails, watcher stops, final
    record written, trace dumped)."""

    def __init__(self, cfg, scorer, batcher, server, watcher, telemetry,
                 writer, heartbeat, build, tracer=None,
                 alert_engine=None, blackbox=None, capture=None):
        self.cfg = cfg
        self.scorer = scorer
        self.batcher = batcher
        self.server = server
        self.watcher = watcher
        self.telemetry = telemetry
        self.port = server.port
        self.alert_engine = alert_engine
        self.blackbox = blackbox
        self.capture = capture
        self.exception: Optional[BaseException] = None
        self._writer = writer
        self._heartbeat = heartbeat
        self._build = build
        self._tracer = tracer
        self._closed = False

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.server.close()
        if self.watcher is not None:
            self.watcher.close()
        self.batcher.close()
        if self._heartbeat is not None:
            self._heartbeat.close()
        if self._writer is not None or self.blackbox is not None:
            try:
                final = self._build("final")
                if final is not None:
                    if self.exception is not None:
                        # Crash-truthful final: same contract as the
                        # trainer's try/finally final record.
                        final["exception"] = type(
                            self.exception
                        ).__name__
                        final["exception_msg"] = str(self.exception)
                    if self._writer is not None:
                        self._writer.write(final)
                    if self.blackbox is not None:
                        self.blackbox.observe_record(final)
            except Exception as e:  # noqa: BLE001 - teardown best-effort
                log.warning("serve final record write failed: %s", e)
        # Crash-truthful bundle: an AlertHaltError (or any crash) that
        # tears serving down leaves its forensics behind.  Dumped
        # BEFORE the writer closes so the manifest still reaches the
        # metrics stream; a clean close dumps nothing.
        if (
            self.blackbox is not None
            and self.exception is not None
            and not isinstance(self.exception, KeyboardInterrupt)
        ):
            self.blackbox.incident(
                "crash_" + type(self.exception).__name__
            )
        if self.capture is not None:
            self.capture.close()
        if self._writer is not None:
            self._writer.close()
        if self._tracer is not None and self._tracer.enabled:
            try:
                n = self._tracer.dump(self.cfg.trace_file)
                self._tracer.close()
                log.info(
                    "serve trace written to %s (%d events)",
                    self.cfg.trace_file, n,
                )
            except Exception as e:  # noqa: BLE001 - teardown best-effort
                log.warning("serve trace dump failed: %s", e)


def _serve_block(snap: dict, scorer, batcher, wall: float) -> dict:
    """The ``serve`` record block: flat, numeric, host-side only —
    rendered as ``tffm_serve_*`` by /metrics and summarized by
    tools/report.py.  ``snap`` is the one telemetry snapshot the whole
    record is built from (one instrument-lock walk per scrape, and the
    block can never disagree with ``stages``)."""
    counters = snap.get("counters") or {}
    timers = snap.get("timers") or {}
    gauges = snap.get("gauges") or {}
    lat = timers.get("serve.latency") or {}
    requests = int(counters.get("serve.requests", 0))
    out = {
        "requests": requests,
        "examples": int(counters.get("serve.examples", 0)),
        "batches": int(counters.get("serve.batches", 0)),
        "qps": round(requests / wall, 2) if wall > 0 else 0.0,
        "inflight": int(gauges.get("serve.inflight", 0)),
        "batch_fill": round(batcher.batch_fill, 6),
        "swaps": int(counters.get("serve.swaps", 0)),
        "compiles": int(scorer.compiles),
        "steady_compiles": int(scorer.steady_compiles),
        "recompiles_unexpected": int(
            counters.get("serve.recompiles_unexpected", 0)
        ),
        "truncated_features": int(
            counters.get("serve.truncated_features", 0)
        ),
        # Front-end shape: 0 = thread-per-connection.  In the record
        # block (not only the run header) so any single metrics
        # snapshot says which accept path produced its latencies.
        "http_threads": int(getattr(
            getattr(scorer, "cfg", None), "serve_http_threads", 0
        ) or 0),
        # Which interaction impl the compiled rungs run (autotune
        # surface; a string — /metrics skips it, the JSONL block keeps
        # it) plus the concurrent-warmup accounting: summed compile
        # seconds vs observed wall, whose gap is the wall the
        # concurrent ladder warmup saved.
        "kernel_impl": getattr(scorer, "kernel_impl", "reference"),
        "warmup_wall_s": round(
            float(getattr(scorer, "warmup_wall_s", 0.0)), 4
        ),
        "warmup_compile_s": round(
            float(getattr(scorer, "warmup_compile_s", 0.0)), 4
        ),
    }
    # Quantized-table accounting, emitted only when the scorer owns
    # the gauges (FixedShapeScorer): the device-resident table's real
    # byte footprint and the max |served_fp32 − served_quant| probe
    # error from the last placement (0 = fp32 serving IS the
    # reference, −1 = unknown).  An OverlayScorer registers neither —
    # defaulting its error to 0 would CLAIM exactness for a quantized
    # cold store it never measured.
    if "serve.table_bytes" in gauges:
        out["table_mb"] = round(
            gauges["serve.table_bytes"] / (1 << 20), 3
        )
    if "serve.quant_error_max" in gauges:
        out["quant_error_max"] = round(
            float(gauges["serve.quant_error_max"]), 6
        )
    for key in ("mean_ms", "p50_ms", "p95_ms", "p99_ms", "max_ms"):
        if key in lat:
            out[key] = lat[key]
    if lat.get("count"):
        # Sample-count companions of the percentile keys above: the
        # run-total observations and how many ring samples the
        # percentiles actually summarize (a p99 over 3 requests is a
        # different claim than one over 30k).
        out["latency_count"] = int(lat["count"])
        if "window_n" in lat:
            out["latency_window_n"] = int(lat["window_n"])
    parse = timers.get("serve.parse") or {}
    if "p50_ms" in parse:
        out["parse_p50_ms"] = parse["p50_ms"]
    parse_bin = timers.get("serve.parse_bin") or {}
    if "p50_ms" in parse_bin:
        out["parse_bin_p50_ms"] = parse_bin["p50_ms"]
    return out


def serve(cfg: FmConfig, mesh=None, port: Optional[int] = None
          ) -> ServeHandle:
    """Build and start the full serving stack from a config.

    ``port`` overrides ``cfg.serve_port`` (tests pass 0 for an
    OS-assigned port; the bound port is ``handle.port``).
    """
    # Persistent XLA compilation cache (compile_cache_dir knob),
    # enabled before the scorer's warmup compiles: a replica spawned
    # against a populated cache replays its whole ladder from disk —
    # zero fresh lowers (platform.compile_cache_stats counts both ways).
    if cfg.compile_cache_dir:
        platform.enable_compile_cache(cfg.compile_cache_dir)
    writer = (
        obs.JsonlWriter(cfg.metrics_file) if cfg.metrics_file else None
    )
    telemetry = obs.Telemetry(enabled=cfg.telemetry)
    # Per-request distributed tracing (serve_trace_sample) + any
    # future serve-path spans land here; trace_file unset = the shared
    # no-op tracer, zero behavior change (same contract as training).
    tracer = (
        Tracer(
            enabled=True, process_name="serve",
            rotate_events=cfg.trace_rotate_events,
            rotate_path=cfg.trace_file or None,
        )
        if cfg.trace_file else NULL_TRACER
    )
    slo = SloTracker(
        cfg.serve_slo_p99_ms, cfg.serve_slo_availability,
        telemetry=telemetry,
    )
    # Training→serving skew detection (obs/quality.py): live request
    # sketches judged against the trainer-published reference sketches
    # in serve_manifest.json; the reference re-reads after every hot
    # swap so it always matches the checkpoint being served.  quality
    # off = no monitor, no skew_* keys, byte-identical serving.
    skew = None
    if cfg.quality:
        from fast_tffm_tpu.train.manifest import read_manifest

        def _read_skew_reference(_model=cfg.model_file):
            man = read_manifest(_model)
            if not isinstance(man, dict) or "quality" not in man:
                return None
            return {"step": man.get("step", -1), **man["quality"]}

        skew = obs.ServeSkewMonitor(
            window_examples=cfg.quality_window, telemetry=telemetry,
            read_reference=_read_skew_reference,
        )
        skew.reload_reference()
    # Watcher baseline BEFORE the load: a checkpoint published while we
    # load/warm up must look NEW to the first poll (the scorer may or
    # may not have caught it; re-swapping to the same step is a cheap
    # no-op, serving stale params forever is not).
    manifest_baseline = checkpoint.read_manifest(cfg.model_file)
    try:
        scorer = scorer_lib.make_scorer(
            cfg, mesh=mesh, telemetry=telemetry, writer=writer
        )
        n_compiles = scorer.warmup()
    except BaseException:
        # No servable checkpoint / warmup failure: close the metrics
        # writer behind the raise (callers retrying against a racing
        # model dir must not accumulate leaked fds).
        if writer is not None:
            writer.close()
        if tracer is not NULL_TRACER:
            tracer.close()
        raise
    log.info(
        "scorer ready: checkpoint step %d, ladder %s, %d rung(s) "
        "precompiled — steady-state serving performs zero compiles",
        scorer.step, list(scorer.ladder), n_compiles,
    )
    if cfg.compile_cache_dir:
        stats = platform.compile_cache_stats()
        log.info(
            "compile cache %s: %d hit(s), %d miss(es) during warmup%s",
            stats["dir"], stats["hits"], stats["misses"],
            " — warm spawn, zero fresh lowers"
            if stats["hits"] and not stats["misses"] else "",
        )
    batcher = ServeBatcher(
        scorer, max_batch_wait_ms=cfg.max_batch_wait_ms,
        queue_size=cfg.queue_size, telemetry=telemetry, tracer=tracer,
        slo=slo, quality=skew,
    )
    # Live-traffic capture (serve_capture_sample/serve_capture_file):
    # sampled request/response frame pairs land in a rotating TFC1
    # file for tools/replay.py.  FmConfig guarantees both knobs are set
    # together; unset = None = byte-identical serving (pinned by test).
    capture = None
    if cfg.serve_capture_file:
        capture = wire.CaptureWriter(
            cfg.serve_capture_file, sample=cfg.serve_capture_sample,
            telemetry=telemetry,
        )
    t0 = time.time()

    def build(kind: str = "status"):
        now = time.time()
        wall = max(now - t0, 1e-9)
        # SLO (and skew) gauges refresh BEFORE the snapshot so one
        # scrape sees block keys and gauge spellings agree.  The final
        # record forces a fresh skew compute past the TTL memo.
        slo_block = slo.snapshot()
        skew_block = (
            skew.block(force=(kind == "final"))
            if skew is not None else {}
        )
        snap = telemetry.snapshot()
        serve_block = _serve_block(snap, scorer, batcher, wall)
        serve_block.update(slo_block)
        serve_block.update(skew_block)
        rec = {
            "record": kind,
            "time": now,
            "elapsed": round(wall, 3),
            "step": scorer.step,
            "serve": serve_block,
            "stages": snap,
        }
        if cfg.resource_metrics:
            rec["resource"] = obs.basic_block(t0)
        if alert_engine is not None:
            # Armed-rule states for /status and the per-rule
            # tffm_alert_active gauges (defined below build; every
            # call happens after serve() finishes wiring).
            rec["alerts"] = alert_engine.active_snapshot()
        if tracer.enabled:
            rec["trace_dropped_events"] = tracer.dropped_events
            if cfg.trace_rotate_events:
                rec["trace_windows"] = tracer.windows_written
        return rec

    run_header = {
            "record": "run_header",
            "mode": "serve",
            "time": t0,
            "model_file": cfg.model_file,
            "resume_step": scorer.step,
            "serve_batch_sizes": list(scorer.ladder),
            "max_batch_wait_ms": cfg.max_batch_wait_ms,
            "serve_poll_secs": cfg.serve_poll_secs,
            "serve_transport": cfg.serve_transport,
            # Front-end shape knobs: a fleet's accept path must be
            # reconstructable from any metrics stream (KD discipline).
            "serve_parse_mode": cfg.serve_parse_mode,
            "serve_http_threads": cfg.serve_http_threads,
            "serve_http_acceptors": cfg.serve_http_acceptors,
            "serve_request_queue_size": ObsHTTPServer.request_queue_size,
            "batch_size": cfg.batch_size,
            "telemetry": cfg.telemetry,
            "heartbeat_secs": cfg.heartbeat_secs,
            "quality": cfg.quality,
            "kernel_impl": getattr(scorer, "kernel_impl", "reference"),
            "interaction_impl": cfg.interaction_impl,
            "compile_cache_dir": cfg.compile_cache_dir,
            "serve_capture_sample": cfg.serve_capture_sample,
            "blackbox": cfg.blackbox,
    }
    if writer is not None:
        writer.write(run_header)
    # Incident flight recorder: fixed-memory rings of recent records/
    # alerts feeding alert-triggered (and POST /incident) forensic
    # bundles.  The pid suffix keeps co-hosted replicas sharing one
    # incident_dir collision-free; blackbox=false = None = no rings,
    # no routes, byte-identical serving.
    blackbox = None
    if cfg.blackbox:
        blackbox = obs.Blackbox(
            cfg.incident_dir
            or os.path.join(cfg.model_file, "incidents"),
            suffix=f"pid{os.getpid()}",
            run_header=run_header,
            metrics_render=lambda: obs.render_prometheus(
                build("status")
            ),
            trace_tail_fn=(tracer.tail if tracer.enabled else None),
            capture_tail_fn=(
                capture.tail_bytes if capture is not None else None
            ),
            writer=writer,
            telemetry=telemetry,
        )
    # Alert watchdog riding the serve heartbeat (same contract as the
    # trainer's: FmConfig guarantees heartbeat_secs > 0 when rules are
    # set; breaches write `record: alert`; an action=halt rule arms
    # engine.halted, which serve_forever raises as AlertHaltError —
    # an embedder polls handle.alert_engine itself).  Every emitted
    # alert also reaches the blackbox, which dumps a bundle.
    alert_engine = None
    if cfg.alert_rules:
        alert_engine = obs.AlertEngine(
            obs.parse_rules(cfg.alert_rules), writer=writer,
            on_alert=(
                blackbox.on_alert if blackbox is not None else None
            ),
        )

    def heartbeat_build():
        rec = build("heartbeat")
        if rec is not None:
            # Ring BEFORE the alert engine observes: an alert-triggered
            # bundle must contain the record that breached the rule.
            if blackbox is not None:
                blackbox.observe_record(rec)
            if alert_engine is not None:
                alert_engine.observe(rec)
        return rec

    heartbeat = None
    if cfg.heartbeat_secs > 0:
        heartbeat = obs.Heartbeat(
            cfg.heartbeat_secs, heartbeat_build, writer=writer,
        )
    watcher = None
    try:
        if cfg.serve_poll_secs > 0:
            watcher = CheckpointWatcher(
                cfg, scorer, cfg.serve_poll_secs,
                seen=manifest_baseline,
                # A hot swap changes the model being served; the skew
                # reference must follow it to the new manifest.
                on_swap=(
                    (lambda step: skew.reload_reference())
                    if skew is not None else None
                ),
            )
        server = ServeServer(
            cfg.serve_port if port is None else port,
            batcher, cfg, build, telemetry=telemetry,
            host=cfg.serve_host, scorer=scorer, tracer=tracer,
            slo=slo,
            on_reload=(
                skew.reload_reference if skew is not None else None
            ),
            on_rollback=(
                skew.restore_previous_reference
                if skew is not None else None
            ),
            capture=capture,
            incident=(
                blackbox.incident if blackbox is not None else None
            ),
        )
    except BaseException:
        # A taken port (or watcher failure) must not leak the batcher
        # dispatcher / watcher / heartbeat threads behind the raise.
        if watcher is not None:
            watcher.close()
        batcher.close()
        if heartbeat is not None:
            heartbeat.close()
        if capture is not None:
            capture.close()
        if writer is not None:
            writer.close()
        if tracer is not NULL_TRACER:
            tracer.close()
        raise
    log.info(
        "scoring endpoint listening on %s:%d (POST /score; GET "
        "/metrics, /status, /healthz, /debug/threadz)",
        cfg.serve_host, server.port,
    )
    return ServeHandle(
        cfg, scorer, batcher, server, watcher, telemetry, writer,
        heartbeat, build, tracer=tracer, alert_engine=alert_engine,
        blackbox=blackbox, capture=capture,
    )


def serve_forever(cfg: FmConfig) -> int:
    """CLI entry: serve until interrupted.  SIGTERM and SIGINT both
    close cleanly — a replica torn down by its router's manager
    (terminate -> wait) must still write its final record and dump its
    trace.  An armed ``action: halt`` alert rule stops the process
    with the crash-truthful final record (AlertHaltError), the same
    watchdog contract as training."""
    handle = serve(cfg)
    print(f"serving on {cfg.serve_host}:{handle.port}", flush=True)

    def _sigterm(signum, frame):  # pragma: no cover - signal path
        raise KeyboardInterrupt

    prev = signal.signal(signal.SIGTERM, _sigterm)
    try:
        obs.run_until_halt(handle.alert_engine)
    except KeyboardInterrupt:
        log.info("interrupted; shutting down the scoring endpoint")
    except obs.AlertHaltError as e:
        log.error("HALT: %s", e)
        handle.exception = e
        handle.close()
        signal.signal(signal.SIGTERM, prev)
        return 1
    finally:
        if not handle._closed:
            handle.close()
        signal.signal(signal.SIGTERM, prev)
    return 0
